"""pio-lens: fleet-wide observability primitives.

The fourth observability leg (pulse=serving, xray=compiler,
tower=training, **lens=fleet**): ``deploy --replicas N`` masks replica
failures so well that the operator can no longer see *which* replica is
eating the tail.  This module holds the process-neutral pieces the
router integration (`server/router.py`) and the dashboard build on:

* :func:`parse_prometheus` — the exposition text format parsed BACK
  into the :meth:`~predictionio_tpu.obs.registry.MetricsRegistry.
  dump_state` schema, the exact inverse of
  :func:`~predictionio_tpu.obs.registry.render_state`
  (property-tested: ``parse_prometheus(render_state(s)) == s``).  The
  router's health loop scrapes each replica's ``/metrics`` through
  this, then re-merges with ``registry.merge_states`` — Prometheus-
  style federation where the merged exposition is byte-compatible
  with a single process that saw every observation.
* :class:`BurnRateTracker` / :func:`install_burn_rate` — SLO burn-rate
  gauges ``pio_slo_burn_rate{window}`` derived from latency-histogram
  DELTAS against a configured SLO: burn rate 1.0 means the error
  budget (1 - objective, default objective 0.99) is being spent
  exactly at the sustainable rate; >> 1 over the short window is the
  page-now signal, >> 1 over the long window the ticket signal.
  Installed on both the replicas' end-to-end latency histogram and the
  router's forward round-trip histogram, so the 10k-QPS fleet sweep
  has an alert-ready signal without a rules engine.
* the router metric families (forward round-trip histogram, router
  timeline segment family, replica scrape-error counter) and the
  ``/debug/fleet`` payload provider hook the dashboard's
  ``fleet.html`` renders through.

Pure stdlib; importable from every layer without cycles (same
contract as the rest of ``obs/``).
"""

from __future__ import annotations

import math
import os
import re
import threading
import time
from typing import Callable, Optional, Sequence

from . import get_registry, log_buckets
from .registry import merge_states, render_state
from .timeline import register_segment_family

__all__ = [
    "BurnRateTracker",
    "BURN_WINDOWS",
    "ROUTER_SEGMENTS",
    "fleet_payload",
    "hist_quantile",
    "install_burn_rate",
    "parse_prometheus",
    "render_fleet",
    "set_fleet_provider",
    "state_counter_total",
    "state_histogram",
]

_registry = get_registry()

# -- metric families (pio-lens catalog) -------------------------------------

REPLICA_SCRAPE_ERRORS = _registry.counter(
    "pio_replica_scrape_errors_total",
    "Replica /metrics scrapes the router could not complete or parse "
    "(the last good snapshot keeps standing in the merged exposition)",
    labels=("replica",),
)
ROUTER_FORWARD_SECONDS = _registry.histogram(
    "pio_router_forward_seconds",
    "Router-observed replica round-trip time per forwarded query "
    "(connect + send + replica serve + response read)",
)
ROUTER_SEGMENT_SECONDS = _registry.histogram(
    "pio_router_segment_seconds",
    "Per-request router-path segment durations (admission/forward/"
    "replica/read/write); per-request segments sum to the handler "
    "wall time, the same accounting identity as "
    "pio_serve_segment_seconds",
    labels=("segment",),
    buckets=log_buckets(1e-6, 100.0, per_decade=4),
)
SLO_BURN_RATE = _registry.gauge(
    "pio_slo_burn_rate",
    "Error-budget burn rate per trailing window: (fraction of "
    "requests over the SLO latency) / (1 - objective); 1.0 spends "
    "the budget exactly at the sustainable rate",
    labels=("window",),
)
SLO_TARGET_SECONDS = _registry.gauge(
    "pio_slo_target_seconds",
    "The configured --slo-ms latency objective this process's burn "
    "rates are computed against (0 = no SLO configured)",
)

# the router request taxonomy (display order on fleet.html):
#   admission — loop-thread time: parse + deadline-admission decision
#   forward   — worker-pool queue wait + connect + request send
#   replica   — waiting on the replica (serve time + response headers)
#   read      — draining the response body
#   write     — socket write of the reply back to the client
ROUTER_SEGMENTS = ("admission", "forward", "replica", "read", "write")
register_segment_family("router", ROUTER_SEGMENT_SECONDS,
                        ROUTER_SEGMENTS)


# -- exposition parsing (the scrape half of federation) ---------------------

_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$"
)
_EXEMPLAR_RE = re.compile(
    r"^# EXEMPLAR ([a-zA-Z_:][a-zA-Z0-9_:]*)_bucket\{(.*)\} "
    r'trace_id="((?:[^"\\]|\\.)*)" value=(\S+)(?: ts=(\S+))?$'
)
_ESCAPE_RE = re.compile(r"\\(.)")


def _unescape(v: str) -> str:
    return _ESCAPE_RE.sub(
        lambda m: "\n" if m.group(1) == "n" else m.group(1), v
    )


def _num(tok: str) -> float:
    if tok == "+Inf" or tok == "Inf":
        return float("inf")
    if tok == "-Inf":
        return float("-inf")
    if tok == "NaN":
        return float("nan")
    return float(tok)


def _parse_labels(raw: str) -> list[tuple[str, str]]:
    """``k="v"`` pairs in order; quote-aware so escaped quotes and
    commas/braces INSIDE a label value parse correctly (the naive
    split-on-comma parser in tools/obs_smoke.py stays as an
    independent cross-check)."""
    out = []
    pos = 0
    raw = raw.strip()
    while pos < len(raw):
        m = _LABEL_RE.match(raw, pos)
        if m is None:
            raise ValueError(f"malformed label block at {raw[pos:]!r}")
        out.append((m.group(1), _unescape(m.group(2))))
        pos = m.end()
        if pos < len(raw):
            if raw[pos] != ",":
                raise ValueError(
                    f"expected ',' between labels at {raw[pos:]!r}"
                )
            pos += 1
    return out


def parse_prometheus(text: str) -> dict:
    """Parse text exposition format 0.0.4 back into the
    ``dump_state()`` schema.  Strict by design — a malformed line
    raises ``ValueError`` (the router books a scrape error and keeps
    the replica's last good snapshot), every sample must be preceded by
    its ``# TYPE`` declaration, histogram children must close with a
    ``+Inf`` bucket, and cumulative bucket counts must be monotone.

    Inverse of :func:`~predictionio_tpu.obs.registry.render_state` for
    states with at least one child per family (a child-less labeled
    family renders no sample lines, so its label names are not
    recoverable from text — irrelevant for merging, which unions
    children)."""
    fams: dict[str, dict] = {}
    help_pending: dict[str, str] = {}

    def fam_for_sample(name: str):
        fam = fams.get(name)
        if fam is not None and fam["kind"] != "histogram":
            return fam, None
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix):
                base = fams.get(name[: -len(suffix)])
                if base is not None and base["kind"] == "histogram":
                    return base, suffix
        if fam is not None:  # a bare histogram-family sample line
            raise ValueError(
                f"histogram family {name!r} has a bare sample line"
            )
        return None, None

    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            rest = line[len("# HELP "):]
            name, _, help_text = rest.partition(" ")
            help_pending[name] = help_text
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            if len(parts) != 4:
                raise ValueError(f"malformed TYPE line: {line!r}")
            _, _, name, kind = parts
            if kind not in ("counter", "gauge", "histogram"):
                raise ValueError(
                    f"unsupported metric kind {kind!r} in {line!r}"
                )
            fams[name] = {
                "name": name,
                "help": help_pending.get(name, ""),
                "kind": kind,
                "labelNames": None,
                "children": {},
            }
            continue
        if line.startswith("# EXEMPLAR "):
            m = _EXEMPLAR_RE.match(line)
            if m is None:
                raise ValueError(f"malformed EXEMPLAR line: {line!r}")
            fam = fams.get(m.group(1))
            if fam is None or fam["kind"] != "histogram":
                raise ValueError(
                    f"exemplar for undeclared histogram {m.group(1)!r}"
                )
            labels = _parse_labels(m.group(2))
            le = dict(labels).get("le")
            if le is None:
                raise ValueError(f"exemplar without le: {line!r}")
            key = tuple((k, v) for k, v in labels if k != "le")
            child = fam["children"].get(key)
            if child is None:
                raise ValueError(
                    f"exemplar precedes its bucket samples: {line!r}"
                )
            child.setdefault("exemplars", []).append([
                le, _unescape(m.group(3)), _num(m.group(4)),
                _num(m.group(5)) if m.group(5) is not None else 0.0,
            ])
            continue
        if line.startswith("#"):
            continue  # other comments are legal and ignored
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"malformed sample line: {line!r}")
        name, labels_raw, value_tok = m.group(1), m.group(2), m.group(3)
        labels = _parse_labels(labels_raw or "")
        fam, suffix = fam_for_sample(name)
        if fam is None:
            raise ValueError(
                f"sample {name!r} precedes its # TYPE declaration"
            )
        v = _num(value_tok)
        if fam["kind"] != "histogram":
            key = tuple(labels)
            if key in fam["children"]:
                raise ValueError(
                    f"duplicate sample for {name}{dict(labels)}"
                )
            fam["children"][key] = {
                "labels": [list(kv) for kv in labels],
                "value": v,
            }
            if fam["labelNames"] is None:
                fam["labelNames"] = [k for k, _ in labels]
            continue
        key = tuple((k, x) for k, x in labels if k != "le")
        child = fam["children"].setdefault(key, {
            "labels": [list(kv) for kv in key],
            "_cum": [],
            "_sum": None,
            "_count": None,
        })
        if suffix == "_bucket":
            le = dict(labels).get("le")
            if le is None:
                raise ValueError(f"bucket sample without le: {line!r}")
            child["_cum"].append((le, v))
        elif suffix == "_sum":
            child["_sum"] = v
        else:
            child["_count"] = v
        if fam["labelNames"] is None:
            fam["labelNames"] = [k for k, _ in key]

    families = []
    for fam in fams.values():
        children = []
        for child in fam["children"].values():
            if fam["kind"] != "histogram":
                children.append(child)
                continue
            cum = child["_cum"]
            if not cum or cum[-1][0] != "+Inf":
                raise ValueError(
                    f"histogram {fam['name']} child does not close "
                    "with a +Inf bucket"
                )
            if child["_sum"] is None or child["_count"] is None:
                raise ValueError(
                    f"histogram {fam['name']} child is missing its "
                    "_sum/_count samples"
                )
            bounds, counts, prev = [], [], 0.0
            for le, c in cum:
                if c < prev:
                    raise ValueError(
                        f"histogram {fam['name']}: cumulative bucket "
                        f"counts regressed at le={le}"
                    )
                counts.append(int(c - prev))
                prev = c
                if le != "+Inf":
                    bounds.append(float(le))
            if sorted(bounds) != bounds:
                raise ValueError(
                    f"histogram {fam['name']}: bucket bounds out of "
                    "order"
                )
            if int(child["_count"]) != int(cum[-1][1]):
                raise ValueError(
                    f"histogram {fam['name']}: _count disagrees with "
                    "the +Inf bucket"
                )
            children.append({
                "labels": child["labels"],
                "hist": {
                    "bounds": bounds,
                    "counts": counts,
                    "sum": child["_sum"],
                    "count": int(child["_count"]),
                    "exemplars": child.get("exemplars", []),
                },
            })
        families.append({
            "name": fam["name"],
            "help": fam["help"],
            "kind": fam["kind"],
            "labelNames": fam["labelNames"] or [],
            "children": children,
        })
    return {"families": sorted(families, key=lambda f: f["name"])}


# -- scraped-state readers (the /debug/fleet tail table) --------------------


def state_counter_total(state: dict, name: str,
                        where: Optional[dict] = None) -> float:
    """Sum a counter family's children, optionally filtered by a
    label-subset match (``where={"status": "ok"}``)."""
    total = 0.0
    for fam in state.get("families", ()):
        if fam["name"] != name:
            continue
        for child in fam["children"]:
            labels = {k: v for k, v in (tuple(kv) for kv
                                        in child["labels"])}
            if where and any(labels.get(k) != v
                             for k, v in where.items()):
                continue
            total += child.get("value", 0.0)
    return total


def state_histogram(state: dict, name: str) -> Optional[dict]:
    """The first histogram child of a family (the unlabeled serving
    families have exactly one), or None."""
    for fam in state.get("families", ()):
        if fam["name"] == name and fam["kind"] == "histogram":
            for child in fam["children"]:
                if "hist" in child:
                    return child["hist"]
    return None


def hist_quantile(hist: dict, q: float) -> float:
    """Percentile estimate from a parsed histogram state — the same
    in-bucket linear interpolation ``Histogram.percentile`` makes, so
    the router's per-replica tail table and a replica's own /status
    agree by construction."""
    n = hist["count"]
    if n == 0:
        return float("nan")
    bounds = hist["bounds"]
    rank = (q / 100.0) * n
    cum = 0
    for i, c in enumerate(hist["counts"]):
        if c == 0:
            continue
        if cum + c >= rank:
            if i >= len(bounds):
                return bounds[-1] if bounds else float("nan")
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i]
            frac = (rank - cum) / c
            return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        cum += c
    return bounds[-1] if bounds else float("nan")


def render_fleet(tagged: Sequence[tuple]) -> str:
    """Merge ``[(worker_id, state), ...]`` with ``{replica}`` gauge
    labels and render — the router's ``GET /metrics`` body."""
    return render_state(merge_states(tagged, gauge_label="replica"))


# -- SLO burn rate ----------------------------------------------------------

BURN_WINDOWS = (("1m", 60.0), ("5m", 300.0), ("1h", 3600.0))


def _default_objective() -> float:
    try:
        v = float(os.environ.get("PIO_TPU_SLO_OBJECTIVE", 0.99))
    except ValueError:
        return 0.99
    return v if 0.0 < v < 1.0 else 0.99


class BurnRateTracker:
    """Error-budget burn rate from latency-histogram deltas.

    Keeps a ring of throttled ``(monotonic, total, good)`` samples of
    the underlying histogram (``good`` = observations in buckets whose
    upper bound is <= the SLO — the conservative side: a request in
    the bucket straddling the SLO counts as bad).  ``rate(window_s)``
    takes the delta between now and the oldest retained sample inside
    the window and answers

        ``(bad_fraction over the window) / (1 - objective)``

    so 1.0 burns the budget exactly as fast as the objective allows,
    and a 14x short-window burn is the classic page threshold.  A
    window with no traffic reads 0.0 — no requests, no budget spent.
    Sampling happens lazily at gauge-read (scrape) time, throttled to
    ``min_sample_s``, so the serving hot path never pays for it.
    """

    def __init__(self, snapshot_fn: Callable[[], dict],
                 bounds: Sequence[float], slo_s: float,
                 objective: Optional[float] = None,
                 min_sample_s: float = 1.0):
        if slo_s <= 0:
            raise ValueError(f"slo_s must be > 0, got {slo_s}")
        self.snapshot_fn = snapshot_fn
        self.bounds = tuple(bounds)
        self.slo_s = float(slo_s)
        self.objective = (objective if objective is not None
                          else _default_objective())
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"objective must be in (0, 1), got {self.objective}"
            )
        self.min_sample_s = min_sample_s
        self._lock = threading.Lock()
        self._ring: list[tuple[float, int, int]] = []
        self._horizon = max(w for _, w in BURN_WINDOWS) + 60.0
        # baseline sample at install time: the first window's delta is
        # "traffic since the SLO was armed", not the empty delta of a
        # single self-referential sample
        snap = self.snapshot_fn()
        self._ring.append(
            (time.monotonic() - self.min_sample_s,
             snap["count"], self._good_of(snap))
        )

    def _good_of(self, snap: dict) -> int:
        good = 0
        for b, c in zip(self.bounds, snap["counts"]):
            if b <= self.slo_s * (1.0 + 1e-9):
                good += c
        return good

    def sample(self, now: Optional[float] = None) -> None:
        now = now if now is not None else time.monotonic()
        with self._lock:
            if self._ring and now - self._ring[-1][0] < self.min_sample_s:
                return
        snap = self.snapshot_fn()  # histogram locks: taken OFF our lock
        total, good = snap["count"], self._good_of(snap)
        with self._lock:
            if self._ring and now - self._ring[-1][0] < self.min_sample_s:
                return  # a concurrent scrape sampled first
            self._ring.append((now, total, good))
            cutoff = now - self._horizon
            while len(self._ring) > 2 and self._ring[1][0] <= cutoff:
                self._ring.pop(0)

    def rate(self, window_s: float,
             now: Optional[float] = None) -> float:
        now = now if now is not None else time.monotonic()
        self.sample(now)
        with self._lock:
            if not self._ring:
                return 0.0
            cur = self._ring[-1]
            old = self._ring[0]
            for s in self._ring:
                if s[0] >= now - window_s:
                    break
                old = s
        d_total = cur[1] - old[1]
        if d_total <= 0:
            return 0.0
        bad = d_total - (cur[2] - old[2])
        frac = min(max(bad / d_total, 0.0), 1.0)
        return frac / (1.0 - self.objective)


def install_burn_rate(hist_child, slo_s: float,
                      objective: Optional[float] = None
                      ) -> BurnRateTracker:
    """Wire ``pio_slo_burn_rate{window}`` gauges to a live histogram
    child (``QUERY_LATENCY.child()`` on replicas,
    ``ROUTER_FORWARD_SECONDS.child()`` on the router).  Gauge reads
    drive the lazy sampling; installing twice repoints the gauges
    (last SLO wins — one objective per process)."""
    if math.isnan(slo_s) or slo_s <= 0:
        raise ValueError(f"slo_s must be a positive number, got {slo_s}")
    tracker = BurnRateTracker(
        hist_child.snapshot, hist_child.bounds, slo_s,
        objective=objective,
    )
    SLO_TARGET_SECONDS.child().set(slo_s)
    for name, secs in BURN_WINDOWS:
        SLO_BURN_RATE.labels(window=name).set_function(
            lambda s=secs: tracker.rate(s)
        )
    return tracker


# -- /debug/fleet payload hook (dashboard fleet.html) -----------------------

_fleet_provider: Optional[Callable[[], dict]] = None


def set_fleet_provider(fn: Optional[Callable[[], dict]]) -> None:
    """Install (or clear, with None) the in-process ``/debug/fleet``
    payload provider — a RouterServer registers itself so the
    dashboard's ``fleet.html`` (and any server's ``/debug/fleet``
    mount) can render the fleet view when a router lives in this
    process."""
    global _fleet_provider
    _fleet_provider = fn


def fleet_payload() -> Optional[dict]:
    fn = _fleet_provider
    if fn is None:
        return None
    try:
        return fn()
    except Exception:  # a broken provider must not 500 the mount
        return None
