"""pio-scope: always-on CPU sampling profiler + lock-contention lens.

The fifth observability leg (pulse = request lifecycle, xray =
compiler/device, tower = training, lens = fleet): *where does the CPU
actually go, and which lock do threads queue on?*  The serving router
is a single event loop, the ingest fleet time-slices one GIL-bound
core per worker — at saturation the question that decides what to fix
is per-thread attribution, continuously, in production, at an
overhead too small to argue about.

Three pieces:

* **Sampling profiler.**  A daemon thread wakes at ``PIO_TPU_SCOPE_HZ``
  (default ~67 Hz — deliberately not a divisor of common periodic
  work) and snapshots every thread's Python stack via
  ``sys._current_frames()`` — no tracing hooks, no interpreter
  switches, cost proportional to thread count x stack depth.  Each
  sample is folded into Brendan-Gregg collapsed-stack form
  (``role;file:fn;file:fn``), classified running/waiting by its leaf
  frame (a thread parked in ``threading.py:wait`` is idle, not hot),
  and aggregated into a bounded time-bucketed ring (1 s buckets,
  ``window_s`` deep) so ``GET /debug/pprof?seconds=S`` answers from
  history instantly — it never blocks to "collect for S seconds".
  Threads are keyed by **role**: spawn sites call
  :func:`register_thread_role` ("eventloop", "microbatch_dispatcher",
  "wal_committer", "foldin_runner", "health_loop", "ingest_worker",
  ...); unregistered threads fall back to "main"/"other" so the
  profile is total, not just the instrumented part.  Every sample also
  books ``pio_cpu_thread_samples_total{role,state}`` — the role-level
  CPU split as plain counters, scrapeable without parsing stacks — and
  the sampler self-measures into ``pio_profile_overhead_ratio``
  (cumulative sampling time / wall time, THE number that keeps
  "always-on" honest).

* **Lock-contention lens.**  ``sys._current_frames`` cannot see who
  blocks on which ``threading.Lock`` (the C-level wait has no Python
  frame of its own), so the hot locks are wrapped instead:
  :class:`TimedLock` / :class:`TimedCondition` are drop-ins whose fast
  path is one extra non-blocking ``acquire(False)`` attempt (tens of
  ns).  Only the *contended* path — the one that was going to block
  anyway — pays for timing: every contended wait books
  ``pio_lock_wait_seconds{lock}``, and hold times book
  ``pio_lock_hold_seconds{lock}`` for contended acquisitions plus a
  1-in-``sample_every`` sample of uncontended ones (enough to estimate
  the hold distribution without two clock reads per acquisition).

* **Shared rendering.**  :func:`flamegraph_html` turns folded text
  into a dependency-free zoomable icicle flamegraph (inline JS, no
  CDN) — the dashboard's ``/prof.html`` and ``tools/profcat.py`` emit
  the same template, so a fleet-merged profile and a single process's
  look identical.

Sampling is a *statistical* profile: a 67 Hz sampler attributes CPU
shares accurately over seconds, not individual microsecond events.
The lock lens is a *proxy*: it measures queueing on the wrapped locks,
not the GIL itself — but on a GIL-bound process the wrapped monitor
queues are where the GIL's effects surface as ordering.

Pure stdlib (this module is imported by the event server, piolint
runs, every storage layer); no jax, no package-internal imports
outside ``obs``.
"""

from __future__ import annotations

import collections
import os
import sys
import threading
import time
from typing import Iterable, Optional

from . import get_registry, log_buckets

__all__ = [
    "ScopeProfiler",
    "TimedCondition",
    "TimedLock",
    "ensure_started",
    "flamegraph_html",
    "get_profiler",
    "merge_folded",
    "parse_folded",
    "profiler_running",
    "register_thread_role",
    "set_enabled",
    "thread_roles",
]

_registry = get_registry()

CPU_THREAD_SAMPLES = _registry.counter(
    "pio_cpu_thread_samples_total",
    "Sampling-profiler thread samples by registered role and "
    "leaf-frame state (running = on CPU or runnable, waiting = parked "
    "in a known blocking frame); at a fixed rate the per-role share "
    "of running samples IS the per-role CPU share",
    labels=("role", "state"),
)
PROFILE_OVERHEAD = _registry.gauge(
    "pio_profile_overhead_ratio",
    "Self-measured profiler cost: cumulative time spent taking+folding "
    "samples divided by wall time since the sampler started (the "
    "always-on budget is <= 0.05)",
)
LOCK_WAIT_SECONDS = _registry.histogram(
    "pio_lock_wait_seconds",
    "Time a thread spent blocked acquiring a scope-wrapped hot lock "
    "(contended acquisitions only — the uncontended fast path books "
    "nothing); per logical lock name, not per instance",
    labels=("lock",),
    buckets=log_buckets(1e-6, 10.0, per_decade=4),
)
LOCK_HOLD_SECONDS = _registry.histogram(
    "pio_lock_hold_seconds",
    "Outermost hold duration of a scope-wrapped hot lock (every "
    "contended acquisition + a 1-in-N sample of uncontended ones)",
    labels=("lock",),
    buckets=log_buckets(1e-6, 10.0, per_decade=4),
)


# -- thread roles -----------------------------------------------------------

_roles_lock = threading.Lock()
_roles: dict[int, str] = {}


def register_thread_role(role: str,
                         thread: Optional[threading.Thread] = None) -> None:
    """Tag the calling thread (or ``thread``, if started) with a role
    for profiler attribution.  Idempotent; last registration wins.
    Call it first thing inside the thread's target — a not-yet-started
    Thread has no ident to key on."""
    ident = thread.ident if thread is not None else threading.get_ident()
    if ident is None:
        raise ValueError(
            "thread has no ident yet (not started); register from "
            "inside the thread's target instead"
        )
    with _roles_lock:
        _roles[int(ident)] = str(role)


def thread_roles() -> dict[int, str]:
    """Snapshot of the ident -> role table (debug/status surfaces)."""
    with _roles_lock:
        return dict(_roles)


def _prune_roles(live_idents: Iterable[int]) -> None:
    """Drop registrations for dead threads (per-connection HTTP
    handler threads come and go; the table must not grow forever)."""
    live = set(live_idents)
    with _roles_lock:
        for ident in [i for i in _roles if i not in live]:
            del _roles[ident]


# -- stack folding ----------------------------------------------------------

# a thread whose LEAF frame is one of these is parked, not computing:
# blocking C calls (lock.acquire, select, socket recv, sleep) have no
# Python frame of their own, so the caller's frame is the evidence
_WAIT_FILES = frozenset((
    "threading.py", "queue.py", "selectors.py", "socketserver.py",
    "socket.py", "ssl.py", "subprocess.py", "connection.py",
))
_WAIT_NAMES = frozenset((
    "wait", "wait_for", "select", "poll", "accept", "sleep", "join",
    "recv", "recv_into", "readinto", "settimeout", "getaddrinfo",
    "_wait_for_tstate_lock",
))

_MAX_DEPTH = 64


def _fold(frame) -> tuple[str, str]:
    """``(state, folded)`` for one thread's frame: collapsed-stack
    frames root-first, ``file:function`` per level, sanitized for the
    folded grammar (no ';' or ' ' inside a frame)."""
    parts: list[str] = []
    f = frame
    while f is not None and len(parts) < _MAX_DEPTH:
        code = f.f_code
        fn = code.co_filename
        base = fn.rsplit("/", 1)[-1].rsplit("\\", 1)[-1]
        parts.append(f"{base}:{code.co_name}")
        f = f.f_back
    if f is not None:
        parts.append("(deeper)")
    leaf_file, _, leaf_name = parts[0].partition(":")
    state = (
        "waiting"
        if leaf_file in _WAIT_FILES or leaf_name in _WAIT_NAMES
        else "running"
    )
    parts.reverse()
    folded = ";".join(parts).replace(" ", "_")
    return state, folded


# -- folded-text helpers (shared with profcat) ------------------------------

def parse_folded(text: str) -> dict[str, int]:
    """``{"root;frame;frame": count}`` from collapsed-stack text;
    malformed lines are skipped (merging tolerates partial fetches)."""
    out: dict[str, int] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        stack, _, count = line.rpartition(" ")
        if not stack:
            continue
        try:
            out[stack] = out.get(stack, 0) + int(count)
        except ValueError:
            continue
    return out


def merge_folded(parts: Iterable[dict[str, int]]) -> dict[str, int]:
    out: dict[str, int] = {}
    for p in parts:
        for stack, count in p.items():
            out[stack] = out.get(stack, 0) + count
    return out


def render_folded(agg: dict[str, int]) -> str:
    return "".join(
        f"{stack} {count}\n" for stack, count in sorted(agg.items())
    )


# -- the profiler -----------------------------------------------------------

class ScopeProfiler:
    """See module docstring.  One per process (:func:`get_profiler`);
    tests build private instances and drive :meth:`record_samples`
    directly for deterministic ring contents."""

    def __init__(self, hz: Optional[float] = None, window_s: int = 120,
                 max_keys_per_bucket: int = 4096):
        self.hz = float(hz) if hz else _env_hz()
        self.window_s = int(window_s)
        self.max_keys_per_bucket = int(max_keys_per_bucket)
        # ring of (epoch_second, {(role, state, folded): count});
        # one lock guards ring structure AND bucket dicts — writers
        # are the sampler (one thread), readers copy under the lock
        # and aggregate outside it
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque()
        self._state_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        # overhead accounting: written only by the sampler thread,
        # read by the gauge callback (float reads are atomic in
        # CPython; a torn read here would still be a valid ratio)
        self._cost_s = 0.0
        self._started_mono: Optional[float] = None
        self._samples = 0
        # (role, state) -> counter child, resolved once (labels() is
        # a dict+lock round trip; 67 Hz x threads would feel it)
        self._children: dict[tuple[str, str], object] = {}

    # -- capture -----------------------------------------------------------
    def sample_once(self, now: Optional[float] = None) -> int:
        """Take one sample of every thread; returns threads sampled.
        Public for tests and for one-shot CLI probes."""
        t0 = time.perf_counter()
        frames = sys._current_frames()
        me = threading.get_ident()
        main_ident = threading.main_thread().ident
        with _roles_lock:
            roles = dict(_roles)
        items: list[tuple[str, str, str]] = []
        for ident, frame in frames.items():
            if ident == me:
                continue  # the sampler profiling itself is pure noise
            role = roles.get(ident)
            if role is None:
                role = "main" if ident == main_ident else "other"
            state, folded = _fold(frame)
            items.append((role, state, folded))
        self.record_samples(items, now=now)
        # single-writer (sampler thread) overhead accounting; a torn
        # float read by the gauge is still a valid ratio
        self._cost_s += time.perf_counter() - t0  # piolint: disable=PIO201
        self._samples += 1
        return len(items)

    def record_samples(self, items: Iterable[tuple[str, str, str]],
                       now: Optional[float] = None) -> None:
        """Fold ``(role, state, folded)`` samples into the ring bucket
        for ``now`` and book the role/state counters.  The
        deterministic entry point: tests drive it with synthetic
        stacks and pinned clocks."""
        items = list(items)
        sec = int(now if now is not None else time.time())
        with self._lock:
            if not self._ring or self._ring[-1][0] != sec:
                self._ring.append((sec, {}))
                cutoff = sec - self.window_s
                while self._ring and self._ring[0][0] <= cutoff:
                    self._ring.popleft()
            bucket = self._ring[-1][1]
            for role, state, folded in items:
                key = (role, state, folded)
                if key not in bucket and (
                    len(bucket) >= self.max_keys_per_bucket
                ):
                    key = (role, state, "(truncated)")
                bucket[key] = bucket.get(key, 0) + 1
        for role, state, _ in items:
            child = self._children.get((role, state))
            if child is None:
                child = CPU_THREAD_SAMPLES.labels(role=role, state=state)
                self._children[(role, state)] = child
            child.inc()

    # -- query -------------------------------------------------------------
    def _window(self, lo_sec: int, hi_sec: int) -> dict:
        """Merged ``{(role, state, folded): count}`` over ring buckets
        with ``lo_sec <= epoch_second <= hi_sec``.  Bucket dicts are
        copied under the lock, merged outside it."""
        with self._lock:
            picked = [
                dict(bucket) for sec, bucket in self._ring
                if lo_sec <= sec <= hi_sec
            ]
        agg: dict = {}
        for bucket in picked:
            for key, count in bucket.items():
                agg[key] = agg.get(key, 0) + count
        return agg

    def collapsed(self, seconds: float = 60.0,
                  state: Optional[str] = None,
                  role: Optional[str] = None,
                  now: Optional[float] = None) -> str:
        """Collapsed-stack text for the trailing ``seconds`` window
        (non-blocking — pure ring read).  Lines are
        ``role;file:fn;... count`` with the role as the root frame;
        ``state``/``role`` filter, ``state=None`` merges running and
        waiting samples of the same stack."""
        hi = int(now if now is not None else time.time())
        lo = hi - max(0, int(seconds) - 1)  # N buckets = N seconds
        agg = self._window(lo, hi)
        out: dict[str, int] = {}
        for (r, s, folded), count in agg.items():
            if state is not None and s != state:
                continue
            if role is not None and r != role:
                continue
            stack = f"{r};{folded}"
            out[stack] = out.get(stack, 0) + count
        return render_folded(out)

    def role_totals(self, seconds: float = 60.0,
                    now: Optional[float] = None) -> dict:
        """``{role: {state: samples}}`` over the window — the CPU-split
        table bench_serving --profile stamps per sweep point."""
        hi = int(now if now is not None else time.time())
        lo = hi - max(0, int(seconds) - 1)
        out: dict[str, dict[str, int]] = {}
        for (r, s, _), count in self._window(lo, hi).items():
            d = out.setdefault(r, {})
            d[s] = d.get(s, 0) + count
        return out

    def dominant_stacks(self, t_start: float, t_end: float,
                        top: int = 3,
                        state: str = "running") -> list[dict]:
        """Top folded stacks sampled during a wall window — the flight
        recorder's "what was the process doing while this request was
        slow" annotation.  Buckets are 1 s wide, so the window is
        widened to the covering buckets; a sub-millisecond request
        under load still joins ~one bucket's worth of samples."""
        agg = self._window(int(t_start), int(t_end))
        picked: dict[str, int] = {}
        total = 0
        for (r, s, folded), count in agg.items():
            if state is not None and s != state:
                continue
            total += count
            stack = f"{r};{folded}"
            picked[stack] = picked.get(stack, 0) + count
        ranked = sorted(picked.items(), key=lambda kv: (-kv[1], kv[0]))
        return [
            {
                "stack": stack,
                "count": count,
                "share": round(count / total, 4) if total else 0.0,
            }
            for stack, count in ranked[:top]
        ]

    def overhead_ratio(self) -> float:
        # lock-free gauge read of the single-writer accounting fields:
        # a stale or torn value is still a valid instantaneous ratio
        started = self._started_mono  # piolint: disable=PIO202
        if started is None:
            return 0.0
        wall = time.monotonic() - started
        return self._cost_s / wall if wall > 0 else 0.0  # piolint: disable=PIO202

    def stats(self) -> dict:
        with self._lock:
            buckets = len(self._ring)
        with self._state_lock:
            running = self._thread is not None
        return {
            "running": running,
            "hz": self.hz,
            "windowSec": self.window_s,
            "buckets": buckets,
            "samples": self._samples,
            "overheadRatio": round(self.overhead_ratio(), 5),
            "roles": sorted(set(thread_roles().values())),
        }

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        """Idempotent; installs the overhead gauge and spawns the
        sampler daemon."""
        with self._state_lock:
            if self._thread is not None:
                return
            self._stop_evt = threading.Event()
            self._started_mono = time.monotonic()
            self._cost_s = 0.0
            t = threading.Thread(
                target=self._loop, args=(self._stop_evt,),
                name="scope-sampler", daemon=True,
            )
            self._thread = t
        PROFILE_OVERHEAD.child().set_function(self.overhead_ratio)
        t.start()

    def stop(self) -> None:
        with self._state_lock:
            t = self._thread
            self._thread = None
            if t is None:
                return
            self._stop_evt.set()
        t.join(timeout=2.0)

    def _loop(self, stop_evt: threading.Event) -> None:
        # the event arrives as an argument so a stop()/start() pair
        # can never hand this (old) loop the NEW event
        register_thread_role("scope_sampler")
        interval = 1.0 / max(self.hz, 0.1)
        next_t = time.monotonic()
        n = 0
        while True:
            next_t += interval
            delay = next_t - time.monotonic()
            if delay < -1.0:
                # fell behind (suspend, GC storm): resynchronize
                # instead of burning CPU catching up on stale ticks
                next_t = time.monotonic() + interval
                delay = interval
            if stop_evt.wait(max(delay, 0.0)):
                return
            try:
                self.sample_once()
            except Exception:
                continue  # a weird frame must never kill the sampler
            n += 1
            if n % 256 == 0:
                _prune_roles(sys._current_frames().keys())


def _env_hz() -> float:
    try:
        hz = float(os.environ.get("PIO_TPU_SCOPE_HZ", "67"))
    except ValueError:
        hz = 67.0
    return min(max(hz, 1.0), 250.0)


_profiler = ScopeProfiler()
_enabled = True


def get_profiler() -> ScopeProfiler:
    return _profiler


def set_enabled(enabled: bool) -> None:
    """``--no-profiler`` / ``PIO_TPU_SCOPE=0``: stops the sampler (and
    keeps :func:`ensure_started` a no-op).  The lock lens keeps
    booking — TimedLock's cost lives on the contended path only, and
    losing the contention evidence is never what an opt-out means."""
    global _enabled
    _enabled = bool(enabled)
    if not _enabled:
        _profiler.stop()


def profiler_running() -> bool:
    return _profiler._thread is not None


def ensure_started() -> bool:
    """Start the always-on sampler unless opted out (``--no-profiler``
    flag via :func:`set_enabled`, or ``PIO_TPU_SCOPE=0`` in the
    environment — the knob subprocess fleets inherit).  Every server
    boot path calls this; returns True when the sampler runs."""
    if not _enabled or os.environ.get("PIO_TPU_SCOPE", "1").lower() in (
        "0", "off", "false", "no"
    ):
        return False
    _profiler.start()
    return True


# -- lock-contention lens ---------------------------------------------------

class TimedLock:
    """Drop-in ``threading.Lock`` / ``RLock`` (``reentrant=True``) that
    books contention into ``pio_lock_wait_seconds{lock=name}`` and
    hold times into ``pio_lock_hold_seconds{lock=name}``.

    Fast path: one non-blocking ``acquire(False)`` attempt — success
    means no contention and nothing is booked except a
    1-in-``sample_every`` hold sample.  Failure falls through to a
    timed blocking acquire; that wait (and the subsequent hold) is
    always booked — the contended path was going to park the thread
    anyway, two clock reads are free by comparison.

    Implements the full lock protocol ``threading.Condition`` needs
    (``_is_owned`` / ``_release_save`` / ``_acquire_restore``), so
    ``TimedCondition(name, lock=TimedLock(...))`` times both monitor
    entry and the post-notify reacquisition queue.  Reentrant holds
    are timed outermost-only (a nested with-block is not a second
    hold).
    """

    sample_every = 16  # uncontended hold sampling period

    def __init__(self, name: str, reentrant: bool = False):
        self.name = str(name)
        self._inner = threading.RLock() if reentrant else threading.Lock()
        self._m_wait = LOCK_WAIT_SECONDS.labels(lock=self.name)
        self._m_hold = LOCK_HOLD_SECONDS.labels(lock=self.name)
        self._local = threading.local()
        # incremented only while holding _inner, so plain int is safe
        self._acqs = 0

    # -- core protocol -----------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if self._inner.acquire(False):
            self._note_acquired(contended=False)
            return True
        if not blocking:
            return False
        t0 = time.perf_counter()
        got = self._inner.acquire(True, timeout)
        if got:
            self._m_wait.observe(time.perf_counter() - t0)
            self._note_acquired(contended=True)
        return got

    def release(self) -> None:
        local = self._local
        depth = getattr(local, "depth", 0) - 1
        if depth < 0:
            raise RuntimeError(f"release of un-acquired TimedLock "
                               f"{self.name!r}")
        local.depth = depth
        book = None
        if depth == 0:
            self._acqs += 1  # still holding: increments serialize
            if local.contended or self._acqs % self.sample_every == 0:
                book = time.perf_counter() - local.t_hold
        self._inner.release()
        if book is not None:
            # booked OFF-lock: the registry shard lock never nests
            # inside the wrapped lock, and the waiter behind us is
            # already running
            self._m_hold.observe(book)

    def __enter__(self) -> "TimedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def _note_acquired(self, contended: bool) -> None:
        local = self._local
        depth = getattr(local, "depth", 0)
        local.depth = depth + 1
        if depth == 0:
            local.contended = contended
            local.t_hold = time.perf_counter()

    # -- Condition protocol ------------------------------------------------
    def _is_owned(self) -> bool:
        inner = self._inner
        owned = getattr(inner, "_is_owned", None)
        if owned is not None:
            return owned()
        if inner.acquire(False):
            inner.release()
            return False
        return True

    def _release_save(self):
        """Full release for ``Condition.wait`` — closes out the hold
        (whatever the reentrant depth) and remembers it for restore."""
        local = self._local
        depth = getattr(local, "depth", 0)
        self._acqs += 1
        book = None
        if local.contended or self._acqs % self.sample_every == 0:
            book = time.perf_counter() - local.t_hold
        local.depth = 0
        save = getattr(self._inner, "_release_save", None)
        state = save() if save is not None else self._inner.release()
        if book is not None:
            self._m_hold.observe(book)
        return (state, depth)

    def _acquire_restore(self, saved) -> None:
        """Reacquire after a ``Condition.wait`` wakeup.  The time spent
        here is pure monitor-reacquisition queueing (the notify wait
        itself already ended), so it always books as lock wait."""
        state, depth = saved
        t0 = time.perf_counter()
        restore = getattr(self._inner, "_acquire_restore", None)
        if restore is not None:
            restore(state)
        else:
            self._inner.acquire()
        self._m_wait.observe(time.perf_counter() - t0)
        local = self._local
        local.depth = depth
        local.contended = True
        local.t_hold = time.perf_counter()


class TimedCondition(threading.Condition):
    """``threading.Condition`` over a :class:`TimedLock` monitor: entry
    contention, post-notify reacquisition queueing, and hold times all
    book under ``lock=name``.  Pass ``lock=`` to share an existing
    :class:`TimedLock` (the WAL's cv shares its commit lock)."""

    def __init__(self, name: str, lock: Optional[TimedLock] = None):
        if lock is None:
            lock = TimedLock(name, reentrant=True)
        super().__init__(lock)
        self.name = str(name)


# -- flamegraph template ----------------------------------------------------

_FLAME_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>__TITLE__</title><style>
body{font:13px system-ui,sans-serif;margin:16px;background:#fafafa}
h1{font-size:16px;margin:0 0 2px}
#meta{color:#666;margin-bottom:10px}
#fg{border:1px solid #ddd;background:#fff;position:relative}
.fr{position:absolute;height:17px;overflow:hidden;white-space:nowrap;
 font-size:11px;line-height:17px;padding:0 3px;box-sizing:border-box;
 border:1px solid rgba(255,255,255,.7);cursor:pointer;
 text-overflow:ellipsis}
.fr:hover{filter:brightness(.85)}
#crumb{margin:8px 0;color:#444;min-height:1.2em}
a{color:#06c;text-decoration:none}
.neg{outline:2px solid #2a7}
.pos{outline:2px solid #c33}
</style></head><body>
<h1>__TITLE__</h1>
<div id="meta"></div>
<div id="crumb"></div>
<div id="fg"></div>
<script>
var FOLDED = __FOLDED__;
var BASELINE = __BASELINE__;
function parse(text){var m={};text.split("\\n").forEach(function(l){
  l=l.trim();if(!l||l[0]=="#")return;var i=l.lastIndexOf(" ");
  if(i<0)return;var n=parseInt(l.slice(i+1),10);if(isNaN(n))return;
  var s=l.slice(0,i);m[s]=(m[s]||0)+n;});return m;}
function tree(m){var root={name:"all",value:0,base:0,kids:{}};
  Object.keys(m).forEach(function(stack){var n=m[stack];
    root.value+=n;var cur=root;
    stack.split(";").forEach(function(f){
      var k=cur.kids[f]||(cur.kids[f]={name:f,value:0,base:0,kids:{}});
      k.value+=n;cur=k;});});
  return root;}
function addBase(root,m){Object.keys(m).forEach(function(stack){
  var n=m[stack];root.base+=n;var cur=root;
  stack.split(";").every(function(f){var k=cur.kids[f];
    if(!k)return false;k.base+=n;cur=k;return true;});});}
function color(name){var h=0;for(var i=0;i<name.length;i++)
  h=(h*31+name.charCodeAt(i))>>>0;
  return "hsl("+(h%360)+",62%,"+(68+(h>>9)%14)+"%)";}
var W,fg=document.getElementById("fg"),
    crumb=document.getElementById("crumb"),ROOT,TOTB;
function render(node,path){fg.innerHTML="";W=fg.clientWidth||900;
  var maxd=0;
  function depth(n,d){if(d>maxd)maxd=d;
    Object.keys(n.kids).forEach(function(k){depth(n.kids[k],d+1);});}
  depth(node,0);fg.style.height=((maxd+1)*17+2)+"px";
  function row(n,x,w,d){if(w<0.5)return;
    var e=document.createElement("div");e.className="fr";
    e.style.left=x+"px";e.style.top=(d*17)+"px";e.style.width=w+"px";
    e.style.background=color(n.name);
    var pct=(100*n.value/node.value).toFixed(1);
    var t=n.name+" — "+n.value+" samples ("+pct+"%)";
    if(BASELINE!==null&&TOTB>0){
      var shareA=n.value/ROOT.value,shareB=n.base/TOTB,
          d2=shareA-shareB;
      t+=" | baseline "+(100*shareB).toFixed(1)+"% ("+
         (d2>=0?"+":"")+(100*d2).toFixed(1)+"pp)";
      if(d2>0.02)e.className+=" pos";else if(d2<-0.02)e.className+=" neg";}
    e.title=t;e.textContent=n.name;
    e.onclick=function(ev){ev.stopPropagation();
      render(n,path.concat([n.name]));};
    fg.appendChild(e);
    var cx=x,kids=Object.keys(n.kids).map(function(k){return n.kids[k];})
      .sort(function(a,b){return b.value-a.value;});
    kids.forEach(function(k){var kw=w*k.value/n.value;
      row(k,cx,kw,d+1);cx+=kw;});}
  row(node,0,W,0);
  crumb.innerHTML=path.length>1
    ?path.map(function(p,i){return "<a href='#' data-i='"+i+"'>"+p+
      "</a>";}).join(" &gt; ")
    :"click a frame to zoom";
  crumb.querySelectorAll("a").forEach(function(a){
    a.onclick=function(ev){ev.preventDefault();
      var i=+a.getAttribute("data-i"),n=ROOT,pp=["all"];
      for(var j=1;j<=i;j++){n=n.kids[path[j]];pp.push(path[j]);}
      render(n,pp);};});}
var m=parse(FOLDED);ROOT=tree(m);TOTB=0;
if(BASELINE!==null){var mb=parse(BASELINE);
  TOTB=Object.keys(mb).reduce(function(a,k){return a+mb[k];},0);
  addBase(ROOT,mb);}
document.getElementById("meta").textContent=ROOT.value+
  " samples"+(BASELINE!==null?(" · diff vs baseline ("+TOTB+
  " samples): red = grew >2pp, green = shrank >2pp"):"")+
  " · widths are sample shares · roles are root frames";
render(ROOT,["all"]);
window.onresize=function(){render(ROOT,["all"]);};
</script></body></html>
"""


def flamegraph_html(folded: str, title: str = "pio-scope profile",
                    baseline: Optional[str] = None) -> str:
    """Self-contained flamegraph page for collapsed-stack text; with
    ``baseline`` folded text the page renders share deltas per frame
    (the profcat A/B diff view).  No external assets — servable from
    an air-gapped dashboard or written to a file by profcat."""
    import json as _json

    return (
        _FLAME_PAGE
        .replace("__TITLE__", title.replace("<", "&lt;"))
        .replace("__FOLDED__", _json.dumps(folded))
        .replace("__BASELINE__",
                 _json.dumps(baseline) if baseline is not None else "null")
    )
