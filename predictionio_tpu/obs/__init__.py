"""pio-obs: unified metrics, latency histograms, and trace propagation.

The observability layer every server and workflow reports into
(SURVEY §5 — the Spark-UI/evaluation-dashboard replacement, grown up):

* :mod:`.registry` — process-wide :class:`MetricsRegistry`
  (thread-safe Counter / Gauge / Histogram under sharded locks) with
  Prometheus text exposition; all four HTTP servers mount it at
  ``GET /metrics`` via ``server/http_base.py``.
* :mod:`.trace` — :class:`Tracer`: trace ids minted at the serving
  edge (or taken from the ``X-PIO-Trace`` request header), propagated
  through ``DeliveryQueue`` payloads to the event server, spans
  recorded into a bounded ring + optional JSONL journal under
  ``$PIO_TPU_HOME/telemetry/``.

This module owns the process-wide instances (``get_registry()`` /
``get_tracer()``) and eagerly registers the standard metric families
(the *metric name catalog* in docs/ARCHITECTURE.md) so every process's
``/metrics`` exposes the same schema from its first scrape — ALX-style
run comparability requires identical shapes, not just identical names.

Pure stdlib, no package-internal imports: every other layer may depend
on this module without cycles.
"""

from __future__ import annotations

import contextlib
import os
import time
from pathlib import Path
from typing import Iterator, Optional

from .registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_latency_buckets,
    log_buckets,
)
from .trace import (
    Span,
    TRACE_HEADER,
    Tracer,
    current_trace_id,
    new_trace_id,
    trace_scope,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "Span",
    "TRACE_HEADER",
    "Tracer",
    "configure",
    "current_trace_id",
    "default_latency_buckets",
    "get_registry",
    "get_tracer",
    "log_buckets",
    "metrics_enabled",
    "new_trace_id",
    "phase_span",
    "render_prometheus",
    "set_metrics_enabled",
    "telemetry_home",
    "trace_scope",
]

# breaker-state gauge encoding (pio_breaker_state)
BREAKER_STATE_VALUES = {"closed": 0.0, "half-open": 1.0, "open": 2.0}


def telemetry_home() -> Path:
    home = os.environ.get("PIO_TPU_HOME") or os.path.expanduser(
        "~/.predictionio_tpu"
    )
    return Path(home) / "telemetry"


def _default_journal_dir() -> Optional[Path]:
    explicit = os.environ.get("PIO_TPU_TELEMETRY_DIR")
    if explicit:
        return Path(explicit)
    if os.environ.get("PIO_TPU_TELEMETRY") == "1":
        return telemetry_home()
    return None


_registry = MetricsRegistry()
_tracer = Tracer(journal_dir=_default_journal_dir())
_metrics_enabled = True


def get_registry() -> MetricsRegistry:
    return _registry


def get_tracer() -> Tracer:
    return _tracer


def metrics_enabled() -> bool:
    return _metrics_enabled


def set_metrics_enabled(enabled: bool) -> None:
    """``/metrics`` answers 404 while disabled (``--no-metrics``);
    recording keeps working — disabling exposition must not change
    what the process measures."""
    global _metrics_enabled
    _metrics_enabled = bool(enabled)


def configure(journal_dir: Optional[os.PathLike | str] = None,
              metrics: Optional[bool] = None) -> None:
    """CLI-facing knob bundle (``--telemetry-dir`` / ``--no-metrics``).
    ``None`` leaves a setting unchanged."""
    if journal_dir is not None:
        _tracer.configure(journal_dir)
    if metrics is not None:
        set_metrics_enabled(metrics)


_cluster_renderer = None


def set_cluster_renderer(fn) -> None:
    """Install (or clear, with ``None``) a callable that renders the
    CLUSTER-merged exposition in place of the local registry's.  Set by
    a pio-tower chief session during a multi-worker training run so
    worker 0's ``/metrics`` shows cluster-wide sums while the run is
    live; local recording is untouched."""
    global _cluster_renderer
    _cluster_renderer = fn


def render_prometheus() -> str:
    fn = _cluster_renderer
    if fn is not None:
        try:
            return fn()
        except Exception:
            pass  # a broken merge must not 500 /metrics
    return _registry.render_prometheus()


# -- standard families (the metric name catalog) ---------------------------
# Registered at import so every process's /metrics carries the full
# schema (zero-valued until first use).  Servers/workflows fetch these
# by the same names — idempotent registration returns the same family.

QUERY_LATENCY = _registry.histogram(
    "pio_query_latency_seconds",
    "End-to-end /queries.json serving latency (decode -> predict -> "
    "serve -> encode)",
)
QUERIES_TOTAL = _registry.counter(
    "pio_queries_total",
    "Serving queries by outcome",
    labels=("status",),
)
ENGINE_QUERIES_TOTAL = _registry.counter(
    "pio_engine_queries_total",
    "Serving queries by registered engine (pio-forge spec name; "
    "'custom' for engines built outside the registry) and outcome",
    labels=("engine", "status"),
)
RELOADS_TOTAL = _registry.counter(
    "pio_reloads_total",
    "Hot model reloads by outcome",
    labels=("result",),
)
BREAKER_STATE = _registry.gauge(
    "pio_breaker_state",
    "Circuit-breaker state per delivery queue "
    "(0=closed, 1=half-open, 2=open)",
    labels=("queue",),
)
DELIVERY_DEPTH = _registry.gauge(
    "pio_delivery_queue_depth",
    "Entries waiting in a bounded delivery queue",
    labels=("queue",),
)
DELIVERY_TOTAL = _registry.counter(
    "pio_delivery_total",
    "Delivery-queue outcomes (submitted/delivered/dropped/retried)",
    labels=("queue", "outcome"),
)
EVENTS_TOTAL = _registry.counter(
    "pio_events_requests_total",
    "Event-server bookkept requests by HTTP status",
    labels=("status",),
)
EVENT_WRITE_LATENCY = _registry.histogram(
    "pio_event_write_latency_seconds",
    "Event-store write latency on the ingestion path",
)
RESILIENCE_TOTAL = _registry.counter(
    "pio_resilience_events_total",
    "Recovered-from trouble (retries etc.) by kind",
    labels=("kind",),
)
TRAIN_PHASE_SECONDS = _registry.histogram(
    "pio_train_phase_seconds",
    "Workflow phase durations (train.run, eval.sweep, als.*)",
    labels=("phase",),
    buckets=log_buckets(1e-4, 10000.0, per_decade=4),
)

# pio-live (incremental fold-in) families: the daemon side books cycles
# / scanned events / produced rows + per-phase timings; the serving side
# books delta applies and keeps the freshness/lag gauges live.  Gauges
# read 0 until pio-live runs — the fields stay absent from status JSON
# when the subsystem is off, but the /metrics schema is always complete.
FOLDIN_CYCLES_TOTAL = _registry.counter(
    "pio_foldin_cycles_total",
    "Fold-in daemon cycles by outcome (ok/empty/error)",
    labels=("result",),
)
FOLDIN_EVENTS_TOTAL = _registry.counter(
    "pio_foldin_events_total",
    "Events consumed past the fold-in watermark",
)
FOLDIN_ROWS_TOTAL = _registry.counter(
    "pio_foldin_rows_total",
    "Factor rows produced by fold-in solves",
    labels=("side", "kind"),  # side=user|item, kind=patched|appended
)
FOLDIN_PHASE_SECONDS = _registry.histogram(
    "pio_foldin_phase_seconds",
    "Fold-in phase durations (live.scan/solve/publish/apply)",
    labels=("phase",),
    buckets=log_buckets(1e-4, 1000.0, per_decade=4),
)
FOLDIN_APPLIES_TOTAL = _registry.counter(
    "pio_foldin_applies_total",
    "Serving-side delta applications by outcome",
    labels=("result",),
)
MODEL_FRESHNESS_SECONDS = _registry.gauge(
    "pio_model_freshness_seconds",
    "Seconds since the serving model last advanced "
    "(full load or applied fold-in delta)",
)
FOLDIN_WATERMARK_LAG = _registry.gauge(
    "pio_foldin_watermark_lag",
    "Event-store rows written past the last applied fold-in watermark",
)

# pio-armor (straggler-tolerant distributed) families: the coded-shard
# orchestration books every parity serve / frozen write, and the
# per-shard lag histogram captures how long the host waited on a shard
# before degrading (the straggler evidence a pod operator reads first).
SHARD_DEGRADED_TOTAL = _registry.counter(
    "pio_shard_degraded_total",
    "Half-iterations / top-k hops where a shard was served from parity "
    "instead of its owner (straggler or dead worker)",
    labels=("shard",),
)
SHARD_LAG_SECONDS = _registry.histogram(
    "pio_shard_lag_seconds",
    "Host-observed wait on a late shard before serving it from parity "
    "(op = als.half | topk.ring)",
    labels=("op",),
    buckets=log_buckets(1e-4, 100.0, per_decade=4),
)

# pio-surge (event-loop serving edge + replica fleet) families: the
# connection-cap guard books refusals per server edge, and the router
# process keeps per-replica health/freshness gauges + forward counters
# (each replica's own registry still exports the unlabeled
# pio_model_freshness_seconds; the router's labeled view is what an
# operator alerts on fleet-wide).
HTTP_OPEN_CONNECTIONS = _registry.gauge(
    "pio_http_open_connections",
    "Open client connections per HTTP server edge",
    labels=("server",),
)
HTTP_CONN_REJECTED = _registry.counter(
    "pio_http_connections_rejected_total",
    "Connections refused with a structured 503 because the per-server "
    "concurrent-connection cap was reached (slow-loris guard)",
    labels=("server",),
)
REPLICA_UP = _registry.gauge(
    "pio_replica_up",
    "Router view of replica health (1=healthy, 0=down)",
    labels=("replica",),
)
REPLICA_MODEL_FRESHNESS = _registry.gauge(
    "pio_replica_model_freshness_seconds",
    "Router-observed per-replica model freshness (seconds since that "
    "replica's model last advanced, read off its health-check status)",
    labels=("replica",),
)
REPLICA_REQUESTS_TOTAL = _registry.counter(
    "pio_replica_requests_total",
    "Requests the router forwarded per replica by outcome "
    "(ok/error/failover)",
    labels=("replica", "outcome"),
)
ROUTER_ADMISSION_TOTAL = _registry.counter(
    "pio_router_admission_total",
    "Router-level deadline admission decisions (admitted / rejected = "
    "a structured 503 answered WITHOUT burning a replica round trip)",
    labels=("outcome",),
)
REPLICA_RESPAWNS_TOTAL = _registry.counter(
    "pio_replica_respawns_total",
    "Dead replica processes the router's supervisor respawned "
    "(capped exponential backoff between attempts)",
    labels=("replica",),
)

# pio-scout (two-stage quantized ANN retrieval) family: the retrieval
# layer books per-stage device time so pulse timelines decompose the
# new path — candidate = quantized shortlist scan (int8 flat or IVF),
# rerank = exact f32 top-k over the gathered shortlist.  Without
# PIO_TPU_TRACE_RETRIEVAL=1 the split is dispatch-attributed (stages
# pipeline on the device queue); with it, each stage is fenced.
RETRIEVAL_STAGE_SECONDS = _registry.histogram(
    "pio_retrieval_stage_seconds",
    "Two-stage ANN retrieval time per stage (candidate|rerank); fenced "
    "per stage only under PIO_TPU_TRACE_RETRIEVAL=1",
    labels=("stage",),
    buckets=log_buckets(1e-5, 10.0, per_decade=4),
)

# pio-hive (multi-tenant serving + live A/B) families: the tenant
# registry books residency/eviction under its device-memory budget, the
# per-tenant serving path books outcomes and latency under (app,
# variant) labels — the label set that makes one tenant's overload or
# open breaker visible WITHOUT reading another tenant's lines — and the
# online-eval aggregator keeps per-variant impression/conversion counts
# + CTR-style rate fresh for /metrics and the pio-tower manifest.
TENANT_RESIDENT_BYTES = _registry.gauge(
    "pio_tenant_resident_bytes",
    "Accounted host+device bytes of one resident tenant model "
    "(factor tables + cached device arrays)",
    labels=("app", "variant"),
)
TENANT_MEMORY_BUDGET = _registry.gauge(
    "pio_tenant_memory_budget_bytes",
    "Configured device-memory budget the tenant registry evicts "
    "toward (0 = unbounded)",
)
TENANTS_RESIDENT = _registry.gauge(
    "pio_tenants_resident",
    "Tenant models currently resident in the registry",
)
TENANT_LOADS_TOTAL = _registry.counter(
    "pio_tenant_loads_total",
    "Tenant registry lifecycle events (kind=load|evict|overcommit)",
    labels=("app", "variant", "kind"),
)
TENANT_QUERIES_TOTAL = _registry.counter(
    "pio_tenant_queries_total",
    "Per-tenant serving outcomes (the isolation evidence: one "
    "tenant's errors live on its own labels)",
    labels=("app", "variant", "status"),
)
TENANT_QUERY_LATENCY = _registry.histogram(
    "pio_tenant_query_latency_seconds",
    "Per-tenant end-to-end serving latency",
    labels=("app", "variant"),
)
TENANT_QUOTA_REJECTED = _registry.counter(
    "pio_tenant_quota_rejected_total",
    "Queries shed by a tenant's token-bucket quota (structured 429)",
    labels=("app", "variant"),
)
TENANT_PLACEMENT_BALANCE = _registry.gauge(
    "pio_tenant_placement_balance",
    "Jain fairness index over resident tenants' accounted bytes "
    "(pio-confluence placement balance): 1.0 = perfectly even "
    "tenant->memory placement, 1/N = one tenant holds everything, "
    "0 = nothing resident.  Recomputed on every registry load/evict "
    "so the fenced _mt sweep can judge balance beside throughput",
)
VARIANT_REQUESTS_TOTAL = _registry.counter(
    "pio_variant_requests_total",
    "Online-eval impressions: queries served per (app, variant)",
    labels=("app", "variant"),
)
VARIANT_FEEDBACK_TOTAL = _registry.counter(
    "pio_variant_feedback_total",
    "Online-eval conversions: variant-attributed feedback events "
    "scanned back out of the event store",
    labels=("app", "variant"),
)
VARIANT_RATE = _registry.gauge(
    "pio_variant_outcome_rate",
    "Online-eval CTR-style rate per (app, variant): conversions / "
    "impressions over the aggregation window",
    labels=("app", "variant"),
)

# pio-lens satellite (ROADMAP item 3): per-shard event-store
# instrumentation on ShardedSQLiteEventStore — write/scan latency and a
# row-delta gauge per shard, so ingestion skew (one hot shard eating
# the write path) is visible on /metrics before the partitioned
# event-server ingestion work lands on top of it.
STORE_SHARD_WRITE_SECONDS = _registry.histogram(
    "pio_store_shard_write_seconds",
    "Sharded event-store write latency per shard (insert / "
    "insert_batch / insert_raw_rows group commits)",
    labels=("shard",),
    buckets=log_buckets(1e-5, 100.0, per_decade=4),
)
STORE_SHARD_SCAN_SECONDS = _registry.histogram(
    "pio_store_shard_scan_seconds",
    "Sharded event-store find_rows_since scan latency per shard "
    "(serial and parallel=True fan-out)",
    labels=("shard",),
    buckets=log_buckets(1e-5, 100.0, per_decade=4),
)
STORE_SHARD_ROWS = _registry.gauge(
    "pio_store_shard_rows",
    "Rows written minus deleted per shard by THIS process since the "
    "store opened — the write-skew indicator, not a table count",
    labels=("shard",),
)

# pio-pilot (sessions + self-driving experiments) families: the
# nextitem engine's transition store books every event folded through
# the sessionizer and keeps the resident transition-pair count live,
# the autopilot publishes its SPRT log-likelihood-ratio walk per
# (app, variant-pair) plus a decision counter (ramp / veto / conclude /
# hold), and the online-eval aggregator exposes how far its incremental
# conversion cursor trails the store's high water mark.
SESSION_EVENTS_TOTAL = _registry.counter(
    "pio_session_events_total",
    "Events folded through the gap-based sessionizer into a nextitem "
    "transition store (per app id)",
    labels=("app",),
)
SESSION_TRANSITIONS = _registry.gauge(
    "pio_session_transitions",
    "Distinct (prev-item, next-item) transition pairs resident in a "
    "nextitem transition store (per app id)",
    labels=("app",),
)
EXPERIMENT_LLR = _registry.gauge(
    "pio_experiment_llr",
    "Autopilot SPRT log-likelihood-ratio walk position for one app's "
    "provisional leader vs the best challenger (crosses the upper "
    "threshold = leader's lift is significant)",
    labels=("app", "variant"),
)
EXPERIMENT_DECISIONS_TOTAL = _registry.counter(
    "pio_experiment_decisions_total",
    "Autopilot controller decisions per app "
    "(decision=ramp|veto|conclude|hold)",
    labels=("app", "decision"),
)
EXPERIMENT_STATE = _registry.gauge(
    "pio_experiment_state",
    "Autopilot experiment phase per app "
    "(0=collecting, 1=ramping, 2=concluded, 3=frozen-by-guardrail)",
    labels=("app",),
)
ONLINE_EVAL_CURSOR_LAG = _registry.gauge(
    "pio_online_eval_cursor_lag",
    "Event-store rows written past the online-eval conversion scan "
    "cursor (per app) — how stale the variant outcome table is",
    labels=("app",),
)

# pio-levee: the fault-isolated multi-process ingest edge — per-shard
# group-commit WAL (append + fsync before 2xx, batched sqlite commits
# off the request path) plus the router's worker-health view.
WAL_FSYNC_SECONDS = _registry.histogram(
    "pio_wal_fsync_seconds",
    "Ingest WAL group-commit flush latency (serialize + append + "
    "fsync for one leader's group, all touched shard logs)",
    buckets=log_buckets(1e-5, 10.0, per_decade=4),
)
WAL_COMMIT_ROWS = _registry.histogram(
    "pio_wal_commit_rows",
    "Rows per batched sqlite commit drained from the ingest WAL "
    "(bigger batches = the amortization the WAL exists for)",
    buckets=(1, 10, 50, 100, 250, 500, 1000, 2500, 5000, 10000,
             25000, 50000),
)
WAL_BACKLOG_ROWS = _registry.gauge(
    "pio_wal_backlog_rows",
    "Acknowledged (fsynced) rows not yet committed into sqlite — the "
    "crash-replay exposure window, bounded by the commit interval",
)
WAL_REPLAYED_TOTAL = _registry.counter(
    "pio_wal_replayed_total",
    "WAL records replayed into sqlite at startup per shard "
    "(at-least-once: INSERT OR REPLACE dedups by event id)",
    labels=("shard",),
)
INGEST_WORKER_UP = _registry.gauge(
    "pio_ingest_worker_up",
    "Ingest-router view of one shard-owner worker (1 healthy, 0 down)",
    labels=("worker",),
)
INGEST_FORWARD_SECONDS = _registry.histogram(
    "pio_ingest_forward_seconds",
    "Ingest-router forward round trip to a shard-owner worker",
    buckets=log_buckets(1e-4, 60.0, per_decade=4),
)
INGEST_SHARD_UNAVAILABLE_TOTAL = _registry.counter(
    "pio_ingest_shard_unavailable_total",
    "Writes refused with a structured 503 because the owning shard "
    "was down (per shard — the one-shard-down blast-radius meter)",
    labels=("shard",),
)

# materialize the unlabeled children now: a histogram family without a
# child renders no bucket ladder, and the schema contract is that every
# process's first scrape already shows the full (zero-valued) shape
QUERY_LATENCY.child()
EVENT_WRITE_LATENCY.child()
FOLDIN_EVENTS_TOTAL.child()
MODEL_FRESHNESS_SECONDS.child()
FOLDIN_WATERMARK_LAG.child()
WAL_FSYNC_SECONDS.child()
WAL_COMMIT_ROWS.child()
TENANT_PLACEMENT_BALANCE.child()


@contextlib.contextmanager
def phase_span(name: str, attrs: Optional[dict] = None) -> Iterator[dict]:
    """Record one workflow phase BOTH ways: a span in the tracer (trace
    correlation) and an observation in ``pio_train_phase_seconds``
    (run-over-run comparability — iALS++-style solver sweeps are only
    comparable when every run emits the same metric schema)."""
    t0 = time.perf_counter()
    with _tracer.span(name, attrs) as a:
        yield a
    TRAIN_PHASE_SECONDS.labels(phase=name).observe(
        time.perf_counter() - t0
    )


# pio-xray (compiler/device observability + slow-query flight recorder)
# and pio-pulse (request-lifecycle timeline decomposition) import last:
# these modules read this package's shared registry/tracer via
# ``from . import ...`` and register their metric families at import,
# so every process's first scrape carries the full schema.  None
# imports jax at module level — obs stays jax-free.
from . import fleet, runlog, scope, timeline, tower, xray  # noqa: E402
from .flight import FlightRecorder, get_flight_recorder  # noqa: E402

__all__ += [
    "FlightRecorder",
    "fleet",
    "get_flight_recorder",
    "runlog",
    "scope",
    "set_cluster_renderer",
    "timeline",
    "tower",
    "xray",
]
