"""pio-pulse: per-request lifecycle timeline decomposition.

The latency histogram says *how slow* a request was; the flight
recorder says *which* requests were slow; this module says **where the
time went** — every served query carries a :class:`Timeline` of
monotonic segment durations

    ``parse -> auth -> queue_wait -> batch_wait -> device -> serialize
    -> write``

captured with cheap ``perf_counter`` stamps threaded through
``server/http_base.py`` (request edge + socket write),
``server/serving.py`` (decode/admission/serialize) and
``server/microbatch.py`` (per-entry enqueue/claim/run stamps — the
batcher credits the caller's timeline with exactly the queue-wait,
accumulation-wait and device time its entry experienced).  Segment
durations aggregate into the ``pio_serve_segment_seconds{segment}``
histogram family (the event-server ingest path gets the parallel
``pio_events_segment_seconds{segment}``: parse/auth/store_write/reply),
and the per-request segment dict rides the ``serve.query`` span attrs,
so a flight-recorder worst-N entry decomposes into *which segment ate
the time* without any extra capture machinery.

Concurrency saturation is first-class: ``pio_serve_inflight`` (requests
between decode and reply), ``pio_microbatch_queue_depth`` (entries
parked behind the in-flight batch), ``pio_microbatch_batch_size`` /
``pio_microbatch_wait_seconds`` histograms and the
``pio_microbatch_role_total{role}`` leader/follower split together
answer "is the batcher widening concurrency or just queueing it" — the
evidence layer the ROADMAP item-2 async front-end rework must beat.

Accounting invariant: a finished timeline's segments SUM to the
measured end-to-end wall time of the regions it covered (residual time
inside a composite region — e.g. condition-variable wake latency after
a batched device call — is attributed to the region's final segment,
never dropped), so per-segment means read off ``/metrics`` reconcile
with the end-to-end latency histogram instead of silently leaking tail
time.  ``tests/test_timeline.py`` holds the property test.

On-demand deep dive: :func:`capture_profile` (mounted at
``GET /debug/profile?seconds=S`` on all four servers) records a
``jax.profiler`` trace into ``$PIO_TPU_HOME/telemetry/profiles/``;
while a capture is live, the serving path and the micro-batcher wrap
their work in ``jax.profiler.TraceAnnotation`` scopes (:class:`annotate`
— a no-op boolean check otherwise), so timeline segments appear as
named rows in the xplane/perfetto view next to the XLA ops they
dispatched.

Pure stdlib at import; jax loads lazily inside an active capture only.
"""

from __future__ import annotations

import os
import threading
import time
from pathlib import Path
from typing import Optional, Sequence, Tuple

from . import get_registry, log_buckets, telemetry_home

__all__ = [
    "EVENT_SEGMENTS",
    "ProfileBusy",
    "SERVE_SEGMENTS",
    "Timeline",
    "annotate",
    "capture_profile",
    "current_timeline",
    "mark",
    "profiles_dir",
    "profiling_active",
    "register_segment_family",
    "timeline_scope",
]

_registry = get_registry()

# the segment taxonomies (docs/ARCHITECTURE.md "Pulse" lists semantics);
# order here is display order on /pulse.html
SERVE_SEGMENTS = (
    "parse", "auth", "queue_wait", "batch_wait", "device", "serialize",
    "write",
)
EVENT_SEGMENTS = ("parse", "auth", "store_write", "reply")

SERVE_SEGMENT_SECONDS = _registry.histogram(
    "pio_serve_segment_seconds",
    "Per-request serving-path segment durations (parse/auth/queue_wait/"
    "batch_wait/device/serialize/write); per-request segments sum to "
    "the end-to-end handler time",
    labels=("segment",),
)
EVENTS_SEGMENT_SECONDS = _registry.histogram(
    "pio_events_segment_seconds",
    "Per-request event-ingest segment durations "
    "(parse/auth/store_write/reply)",
    labels=("segment",),
)
SERVE_INFLIGHT = _registry.gauge(
    "pio_serve_inflight",
    "Queries currently inside predict_json (decode -> serialize): the "
    "serving edge's concurrency saturation gauge",
)
MICROBATCH_QUEUE_DEPTH = _registry.gauge(
    "pio_microbatch_queue_depth",
    "Entries waiting in the micro-batcher's pending list (parked "
    "behind the in-flight batch)",
)
MICROBATCH_BATCH_SIZE = _registry.histogram(
    "pio_microbatch_batch_size",
    "Dispatched micro-batch sizes (pre-padding: what actually "
    "coalesced)",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512),
)
MICROBATCH_WAIT_SECONDS = _registry.histogram(
    "pio_microbatch_wait_seconds",
    "Per-batch wait from first claim to device dispatch (the "
    "accumulation-window cost)",
    buckets=log_buckets(1e-6, 10.0, per_decade=4),
)
MICROBATCH_ROLE_TOTAL = _registry.counter(
    "pio_microbatch_role_total",
    "Requests by batcher role: the leader ran the device call on its "
    "own thread, a follower's result came from another thread's batch, "
    "a dispatched request rode the continuous dispatcher (pio-surge "
    "event-loop edge — no request thread involved)",
    labels=("role",),
)
MICROBATCH_ADMISSION_TOTAL = _registry.counter(
    "pio_microbatch_admission_total",
    "Deadline-aware admission outcomes (pio-surge): rejected = the "
    "edge answered a structured 503 up front because the estimated "
    "queue+service time exceeded the request deadline; expired = "
    "claimed from the queue already past its deadline and completed "
    "without ever reaching the device",
    labels=("outcome",),
)
MICROBATCH_TENANTS_PER_BATCH = _registry.histogram(
    "pio_microbatch_tenants_per_batch",
    "Distinct tenants coalesced into one shared-batcher dispatcher "
    "claim (pio-confluence): >1 means cross-tenant traffic rode one "
    "dispatcher turn instead of competing per-tenant device queues — "
    "the mixing evidence the hive_smoke gate asserts",
    buckets=(1, 2, 4, 8, 16, 32),
)

# children cached at import: .labels() is a dict build + lock per call
# (~1.5 us), too hot for per-request use — and materializing them keeps
# the /metrics schema complete (zero-valued) from the first scrape
_SEGMENT_CHILDREN = {
    "serve": {
        s: SERVE_SEGMENT_SECONDS.labels(segment=s) for s in SERVE_SEGMENTS
    },
    "events": {
        s: EVENTS_SEGMENT_SECONDS.labels(segment=s)
        for s in EVENT_SEGMENTS
    },
}
def register_segment_family(family: str, histogram_family,
                            segments) -> None:
    """Attach a new timeline family (pio-lens adds ``router``):
    ``Timeline(family)`` instances booked via :meth:`Timeline.finish`
    observe into ``histogram_family{segment=...}`` children, cached
    here once like the serve/events families above."""
    _SEGMENT_CHILDREN[family] = {
        s: histogram_family.labels(segment=s) for s in segments
    }


SERVE_INFLIGHT.child()
MICROBATCH_QUEUE_DEPTH.child()
MICROBATCH_BATCH_SIZE.child()
MICROBATCH_WAIT_SECONDS.child()
MICROBATCH_TENANTS_PER_BATCH.child()
MICROBATCH_ROLE_TOTAL.labels(role="leader")
MICROBATCH_ROLE_TOTAL.labels(role="follower")
MICROBATCH_ROLE_TOTAL.labels(role="dispatched")
MICROBATCH_ADMISSION_TOTAL.labels(outcome="rejected")
MICROBATCH_ADMISSION_TOTAL.labels(outcome="expired")


class Timeline:
    """Monotonic per-request segment accumulator.

    ``mark(seg)`` closes the region since the previous boundary and
    books it under ``seg``; ``add_block(parts, residual_to)`` closes a
    composite region whose interior was measured elsewhere (the
    batcher's entry stamps), crediting the measured parts and the
    residual — wake latency, lock handoff — to ``residual_to`` so the
    segment sum still equals the region's wall time.  Single-threaded
    by construction (one request, one timeline, marked only from the
    thread carrying the request), hence no lock.
    """

    __slots__ = ("family", "segments", "t0", "_last")

    def __init__(self, family: str = "serve"):
        self.family = family
        self.segments: dict[str, float] = {}
        self.t0 = self._last = time.perf_counter()

    def mark(self, segment: str) -> None:
        now = time.perf_counter()
        self.segments[segment] = (
            self.segments.get(segment, 0.0) + (now - self._last)
        )
        self._last = now

    def add_block(self, parts: Sequence[Tuple[str, float]],
                  residual_to: str) -> None:
        now = time.perf_counter()
        total = max(now - self._last, 0.0)
        parts = [(seg, max(dur, 0.0)) for seg, dur in parts]
        acc = sum(dur for _, dur in parts)
        if acc > total:
            # interior stamps can only exceed the region by clock
            # jitter (they are taken inside it); scale proportionally
            # so the sum identity holds UNCONDITIONALLY — the identity
            # is what makes /metrics segment means reconcile with e2e
            scale = total / acc if acc > 0 else 0.0
            parts = [(seg, dur * scale) for seg, dur in parts]
            acc = total
        segs = self.segments
        for seg, dur in parts:
            segs[seg] = segs.get(seg, 0.0) + dur
        segs[residual_to] = segs.get(residual_to, 0.0) + (total - acc)
        self._last = now

    def elapsed(self) -> float:
        return time.perf_counter() - self.t0

    def snapshot_ms(self) -> dict:
        """Rounded-ms view for span attrs / flight records (small JSON,
        human-scannable next to durationSec)."""
        return {k: round(v * 1e3, 3) for k, v in self.segments.items()}

    def finish(self) -> dict:
        """Observe every booked segment into this family's histogram
        children and return the raw segment dict (seconds)."""
        children = _SEGMENT_CHILDREN.get(self.family)
        if children is not None:
            for seg, dur in self.segments.items():
                child = children.get(seg)
                if child is not None:
                    child.observe(dur)
        return dict(self.segments)


# -- thread-local scope (the trace_scope pattern) ---------------------------

_local = threading.local()


def current_timeline() -> Optional[Timeline]:
    return getattr(_local, "tl", None)


class timeline_scope:
    """Bind a timeline to this thread for the duration of the block
    (the micro-batcher and nested marks find it via
    :func:`current_timeline`).  Slotted like ``trace_scope``: this
    wraps every served query."""

    __slots__ = ("tl", "_prev")

    def __init__(self, tl: Optional[Timeline]):
        self.tl = tl

    def __enter__(self) -> Optional[Timeline]:
        self._prev = getattr(_local, "tl", None)
        _local.tl = self.tl
        return self.tl

    def __exit__(self, *exc) -> None:
        _local.tl = self._prev


def mark(segment: str) -> None:
    """Mark a boundary on the thread's current timeline; free no-op
    when no timeline is in scope (direct library calls, tests)."""
    tl = getattr(_local, "tl", None)
    if tl is not None:
        tl.mark(segment)


# -- on-demand jax.profiler capture ----------------------------------------


class ProfileBusy(RuntimeError):
    """A capture is already in flight (one per process — concurrent
    jax.profiler traces are not supported)."""


_capture_lock = threading.Lock()
_profiling = False  # bare bool read on the hot path (GIL-atomic)


def profiling_active() -> bool:
    return _profiling


class annotate:
    """``jax.profiler.TraceAnnotation`` bridge: a named scope that
    appears in the xplane while a :func:`capture_profile` is live and
    costs one module-bool check otherwise.  Never raises — a jax-free
    process simply produces no annotation."""

    __slots__ = ("name", "_cm")

    def __init__(self, name: str):
        self.name = name
        self._cm = None

    def __enter__(self) -> "annotate":
        if _profiling:
            try:
                import jax.profiler

                self._cm = jax.profiler.TraceAnnotation(self.name)
                self._cm.__enter__()
            except Exception:
                self._cm = None
        return self

    def __exit__(self, *exc) -> None:
        if self._cm is not None:
            cm, self._cm = self._cm, None
            cm.__exit__(*exc)


def profiles_dir() -> Path:
    return telemetry_home() / "profiles"


def capture_profile(seconds: float,
                    out_dir: Optional[os.PathLike | str] = None) -> dict:
    """Blocking on-demand profiler capture (``GET /debug/profile``).

    Records a ``jax.profiler`` trace for ``seconds`` (clamped to
    [0.05, 60] — a scrape typo must not wedge a handler thread for an
    hour) into a fresh timestamped directory under
    ``telemetry/profiles/``, with :class:`annotate` scopes live so
    timeline segments land in the xplane.  Raises :class:`ProfileBusy`
    when a capture is already running; any profiler failure propagates
    to the caller (the HTTP mount answers 500 — a broken profiler must
    be loud, not an empty artifact)."""
    global _profiling
    seconds = min(max(float(seconds), 0.05), 60.0)
    if not _capture_lock.acquire(blocking=False):
        raise ProfileBusy("a profile capture is already running")
    try:
        import jax.profiler

        base = Path(out_dir) if out_dir is not None else profiles_dir()
        base.mkdir(parents=True, exist_ok=True)
        stamp = time.strftime("%Y%m%d-%H%M%S", time.gmtime())
        target = base / f"{stamp}-pid{os.getpid()}"
        jax.profiler.start_trace(str(target))
        _profiling = True
        try:
            time.sleep(seconds)
        finally:
            _profiling = False
            jax.profiler.stop_trace()
        files = sorted(
            str(p.relative_to(target))
            for p in target.rglob("*") if p.is_file()
        )
        total = sum((target / f).stat().st_size for f in files)
        return {
            "dir": str(target),
            "seconds": seconds,
            "files": files,
            "totalBytes": total,
        }
    finally:
        _capture_lock.release()
