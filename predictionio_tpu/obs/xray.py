"""pio-xray: compiler + device observability.

The layer below pio-obs's request metrics — the XLA compiler and the
device — fails silently: a shape-churn recompile or an OOM-adjacent
allocator shows up only as a mysteriously slow histogram bucket (the
exact failure mode ALX calls out for TPU-resident factorization).
This module makes both visible:

* **Compile observability.**  ``install()`` hooks ``jax.monitoring``:
  every backend compile lands in ``pio_jit_compile_seconds`` and
  increments ``pio_jit_compiles_total{fn}``; compilation-cache events
  (hit / miss / request) land in ``pio_compile_cache_events_total
  {kind}`` so cold-start and warm-start deploys are distinguishable on
  ``/metrics``.  Attribution of a compile to a *function* rides a
  thread-local set by :func:`instrument`-wrapped entry points (the
  repo's jitted ALS halves, the fused gather+Gram+solve kernel's
  pallas entries — ``als.fused``, whose signature carries the tile
  plan, table dtype, precision, and gather-impl statics, so a fused
  recompile's per-arg delta names exactly which of them churned — and
  the top-k scorers); compiles outside any tracked call book under
  ``fn="untracked"``.
* **Recompilation detector.**  :func:`instrument` wraps a jitted
  callable and fingerprints every call's arg signature (shapes /
  dtypes / static kwargs).  A signature never seen before means XLA is
  about to compile; the event — including the **delta** against the
  previous signature (which arg changed, from what, to what) and the
  current trace id — is recorded into a bounded ring surfaced at
  ``GET /debug/xray``.  "Why did my query recompile?" is answered by
  one curl instead of an XLA log safari.
* **Device observability.**  :func:`sample_devices_once` reads
  ``device.memory_stats()`` per device (bytes-in-use / peak / limit)
  into ``pio_device_memory_bytes{device,stat}``; backends without
  allocator stats (CPU) fall back to summing live-array bytes per
  device, so the gauges exist on every backend.
  :func:`start_sampler` runs it on a daemon thread, registered at
  server/workflow boot the way the delivery-queue breaker gauges are.
* **Optional cost analysis.**  With ``PIO_TPU_XRAY_COST=1``, each new
  signature of an instrumented fn is AOT-lowered once for
  ``cost_analysis()`` FLOP/byte estimates
  (``pio_jit_fn_cost{fn,kind}``) — opt-in because it duplicates the
  trace work.

No module-level jax import: ``obs`` stays importable from jax-free
processes (piolint, the event server); jax loads lazily inside
``install()`` / the sampler / the wrappers' first use, all of which
only run in processes that already traced something.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Callable, Optional

from . import get_registry, log_buckets
from .trace import current_trace_id

__all__ = [
    "device_high_water",
    "install",
    "instrument",
    "jit_stats",
    "note_compilation_cache",
    "recompile_events",
    "sample_devices_once",
    "set_sample_period",
    "start_sampler",
    "stop_sampler",
    "total_backend_compiles",
    "xray_payload",
]

_registry = get_registry()

JIT_COMPILES = _registry.counter(
    "pio_jit_compiles_total",
    "XLA backend compiles attributed to the instrumented entry point "
    "that dispatched them (fn=\"untracked\" for compiles outside any "
    "tracked call)",
    labels=("fn",),
)
JIT_COMPILE_SECONDS = _registry.histogram(
    "pio_jit_compile_seconds",
    "XLA backend compile wall time per compile "
    "(/jax/core/compile/backend_compile_duration)",
    buckets=log_buckets(1e-3, 1000.0, per_decade=4),
)
COMPILE_CACHE_EVENTS = _registry.counter(
    "pio_compile_cache_events_total",
    "jax persistent-compilation-cache events (request/hit/miss): "
    "hit/request ~= 1 is a warm start, ~= 0 a cold one",
    labels=("kind",),
)
DEVICE_MEMORY = _registry.gauge(
    "pio_device_memory_bytes",
    "Per-device memory from device.memory_stats() (stat=bytes_in_use/"
    "peak_bytes_in_use/bytes_limit) or, on backends without allocator "
    "stats, summed live-array bytes (stat=live_bytes)",
    labels=("device", "stat"),
)
JIT_FN_COST = _registry.gauge(
    "pio_jit_fn_cost",
    "cost_analysis() estimate for the most recent compile of an "
    "instrumented fn (kind=flops/bytes_accessed; PIO_TPU_XRAY_COST=1)",
    labels=("fn", "kind"),
)

# the full schema appears on every process's first scrape (pio-obs
# contract); the unlabeled histogram child must exist for its ladder
JIT_COMPILE_SECONDS.child()

_CACHE_EVENT_KINDS = {
    "/jax/compilation_cache/cache_hits": "hit",
    "/jax/compilation_cache/cache_misses": "miss",
    "/jax/compilation_cache/compile_requests_use_cache": "request",
    "/jax/compilation_cache/tasks_using_cache": "task_using_cache",
    "/jax/compilation_cache/task_disabled_cache": "task_disabled",
}
_COMPILE_DURATION_EVENT = "/jax/core/compile/backend_compile_duration"


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


# -- call-signature fingerprinting ----------------------------------------


def _key_leaf(x):
    """Hashable structural key for one argument (cheap — runs on every
    instrumented call)."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return ("arr", tuple(shape), str(dtype))
    if isinstance(x, (tuple, list)):
        return tuple(_key_leaf(v) for v in x)
    if isinstance(x, dict):
        return tuple((k, _key_leaf(v)) for k, v in sorted(x.items()))
    if isinstance(x, (int, float, bool, str, bytes, type(None))):
        return x
    return (type(x).__name__, repr(x)[:64])


def _sig_key(args: tuple, kwargs: dict) -> tuple:
    return (
        tuple(_key_leaf(a) for a in args),
        tuple((k, _key_leaf(v)) for k, v in sorted(kwargs.items())),
    )


def _describe_leaf(x) -> str:
    """Human descriptor for the recompile ring (runs only on new
    signatures)."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return f"{dtype}[{','.join(str(d) for d in shape)}]"
    if isinstance(x, (tuple, list)):
        inner = ",".join(_describe_leaf(v) for v in x)
        return f"({inner})"
    if isinstance(x, dict):
        inner = ",".join(
            f"{k}={_describe_leaf(v)}" for k, v in sorted(x.items())
        )
        return f"{{{inner}}}"
    r = repr(x)
    return r if len(r) <= 64 else r[:61] + "..."


def _describe_call(args: tuple, kwargs: dict) -> tuple:
    """``((label, descriptor), ...)`` — positional args by index,
    static/keyword args by name."""
    out = [(f"arg{i}", _describe_leaf(a)) for i, a in enumerate(args)]
    out += [(k, _describe_leaf(v)) for k, v in sorted(kwargs.items())]
    return tuple(out)


def signature_delta(old: Optional[tuple], new: tuple) -> Optional[dict]:
    """What changed between two described signatures — the payload an
    operator reads to learn which arg's shape churned."""
    if old is None:
        return None
    od, nd = dict(old), dict(new)
    changed = [
        {"arg": k, "from": od[k], "to": nd[k]}
        for k in nd if k in od and od[k] != nd[k]
    ]
    added = [{"arg": k, "value": nd[k]} for k in nd if k not in od]
    removed = [{"arg": k, "value": od[k]} for k in od if k not in nd]
    return {"changed": changed, "added": added, "removed": removed}


# -- state ------------------------------------------------------------------

_tl = threading.local()  # .fn = name of the instrumented call in flight


class _XrayState:
    """All mutable pio-xray bookkeeping under one lock (none of it is
    on a sub-microsecond path; compiles and new signatures are rare)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._installed = False
        self._install_error: Optional[str] = None
        self._fns: dict[str, dict] = {}
        self._ring: collections.deque = collections.deque(
            maxlen=_env_int("PIO_TPU_XRAY_RING", 64)
        )
        self._cache_events: dict[str, int] = {}
        self._cache_dir: Optional[str] = None
        self._devices: list = []
        self._devices_at: Optional[float] = None
        self._sampler: Optional[threading.Thread] = None
        self._sampler_stop: Optional[threading.Event] = None
        self._sample_period = _env_float("PIO_TPU_XRAY_SAMPLE_S", 10.0)

    # -- fn tracking -------------------------------------------------------
    def _fn_state_locked(self, name: str) -> dict:
        st = self._fns.get(name)
        if st is None:
            st = {
                "calls": 0,
                "signatures": set(),
                "last_described": None,
                "backend_compiles": 0,
                "compile_seconds_total": 0.0,
                "last_compile_seconds": None,
                "cost": None,
            }
            self._fns[name] = st
        return st

    def observe_call(self, name: str, key: tuple) -> bool:
        """Count one call; True when the signature is (probably) new —
        the caller then builds the pretty descriptors and calls
        :meth:`register_signature`, which re-checks atomically."""
        with self._lock:
            st = self._fn_state_locked(name)
            st["calls"] += 1
            return key not in st["signatures"]

    def register_signature(self, name: str, key: tuple,
                           described: tuple) -> Optional[dict]:
        """Atomically admit a new signature; returns the ring entry
        (None when a concurrent call already registered it)."""
        with self._lock:
            st = self._fn_state_locked(name)
            if key in st["signatures"]:
                return None
            prev = st["last_described"]
            st["signatures"].add(key)
            st["last_described"] = described
            nth = len(st["signatures"])
            entry = {
                "fn": name,
                "at": time.time(),
                "traceId": current_trace_id(),
                "kind": "compile" if nth == 1 else "recompile",
                "nthSignature": nth,
                "signature": [
                    {"arg": k, "value": v} for k, v in described
                ],
                "delta": signature_delta(prev, described),
            }
            self._ring.append(entry)
            return entry

    def note_backend_compile(self, name: Optional[str],
                             duration_s: float) -> None:
        with self._lock:
            st = self._fn_state_locked(name or "untracked")
            st["backend_compiles"] += 1
            st["compile_seconds_total"] += duration_s
            st["last_compile_seconds"] = duration_s

    def set_cost(self, name: str, cost: dict) -> None:
        with self._lock:
            self._fn_state_locked(name)["cost"] = dict(cost)

    # -- misc notes --------------------------------------------------------
    def note_cache_event(self, kind: str) -> None:
        with self._lock:
            self._cache_events[kind] = self._cache_events.get(kind, 0) + 1

    def note_cache_dir(self, cache_dir: Optional[str]) -> None:
        with self._lock:
            self._cache_dir = cache_dir

    def set_devices(self, devices: list) -> None:
        with self._lock:
            self._devices = list(devices)
            self._devices_at = time.time()

    def set_sample_period(self, period_s: float) -> None:
        with self._lock:
            self._sample_period = float(period_s)

    # -- install / sampler lifecycle --------------------------------------
    def claim_install(self) -> bool:
        """True when this call won the (single) install slot."""
        with self._lock:
            if self._installed:
                return False
            self._installed = True
            return True

    def set_install_error(self, error: Optional[str]) -> None:
        with self._lock:
            self._install_error = error

    def installed(self) -> bool:
        with self._lock:
            return self._installed and self._install_error is None

    def sampler_slot(self) -> Optional[threading.Event]:
        """Claim the sampler slot; None when one is already running or
        sampling is disabled (period <= 0)."""
        with self._lock:
            if self._sampler is not None and self._sampler.is_alive():
                return None
            if self._sample_period <= 0:
                return None
            self._sampler_stop = threading.Event()
            return self._sampler_stop

    def set_sampler(self, thread: Optional[threading.Thread]) -> None:
        with self._lock:
            self._sampler = thread

    def sampler_state(self) -> tuple:
        with self._lock:
            return self._sampler_stop, self._sample_period

    # -- snapshots ---------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            fns = {
                name: {
                    "calls": st["calls"],
                    "signatures": len(st["signatures"]),
                    "backendCompiles": st["backend_compiles"],
                    "compileSecondsTotal": round(
                        st["compile_seconds_total"], 6),
                    "lastCompileSeconds": st["last_compile_seconds"],
                    **({"cost": st["cost"]} if st["cost"] else {}),
                }
                for name, st in self._fns.items()
            }
            return {
                "installed": self._installed,
                "installError": self._install_error,
                "fns": fns,
                "recompiles": list(self._ring),
                "cacheEvents": dict(self._cache_events),
                "cacheDir": self._cache_dir,
                "devices": list(self._devices),
                "devicesSampledAt": self._devices_at,
            }

    def reset_for_tests(self) -> None:
        with self._lock:
            self._fns = {}
            self._ring.clear()
            self._cache_events = {}


_STATE = _XrayState()


# -- jax.monitoring listeners ----------------------------------------------


def _on_duration_event(event: str, duration_s: float, **kw) -> None:
    if event != _COMPILE_DURATION_EVENT:
        return
    fn = getattr(_tl, "fn", None)
    JIT_COMPILE_SECONDS.child().observe(duration_s)
    JIT_COMPILES.labels(fn=fn or "untracked").inc()
    _STATE.note_backend_compile(fn, duration_s)


def _on_event(event: str, **kw) -> None:
    kind = _CACHE_EVENT_KINDS.get(event)
    if kind is not None:
        COMPILE_CACHE_EVENTS.labels(kind=kind).inc()
        _STATE.note_cache_event(kind)


def install() -> bool:
    """Register the jax.monitoring listeners (idempotent, thread-safe).
    Returns True when monitoring is active after the call.  A jax
    without the monitoring API degrades gracefully: instrumented
    wrappers then count their own new-signature compiles."""
    if not _STATE.claim_install():
        return _STATE.installed()
    try:
        import jax.monitoring as monitoring

        monitoring.register_event_duration_secs_listener(
            _on_duration_event
        )
        monitoring.register_event_listener(_on_event)
        return True
    except Exception as e:  # pragma: no cover - jax API drift guard
        _STATE.set_install_error(f"{type(e).__name__}: {e}")
        return False


# -- instrumented jit entry points -----------------------------------------


def _cost_enabled() -> bool:
    return os.environ.get("PIO_TPU_XRAY_COST") == "1"


def _analyze_cost(name: str, fn, args: tuple, kwargs: dict) -> None:
    """Opt-in AOT cost analysis for a freshly-seen signature.  Never
    raises: estimates are advisory, and some backends/fns don't
    support lowering outside a trace."""
    try:
        analysis = fn.lower(*args, **kwargs).compile().cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else {}
        flops = float(analysis.get("flops", 0.0))
        nbytes = float(analysis.get("bytes accessed", 0.0))
        JIT_FN_COST.labels(fn=name, kind="flops").set(flops)
        JIT_FN_COST.labels(fn=name, kind="bytes_accessed").set(nbytes)
        _STATE.set_cost(name, {"flops": flops, "bytesAccessed": nbytes})
    except Exception:
        pass


class _Instrumented:
    """Callable wrapper around a jitted fn: fingerprints each call,
    feeds the recompile detector, and attributes any backend compile
    fired during the call to ``name`` via a thread-local.  Unknown
    attributes (``_cache_size``, ``lower`` ...) delegate to the wrapped
    jit object, so AOT APIs and cache introspection keep working."""

    __slots__ = ("_fn", "_name", "__wrapped__")

    def __init__(self, fn: Callable, name: str):
        self._fn = fn
        self._name = name
        self.__wrapped__ = fn

    def __call__(self, *args, **kwargs):
        name = self._name
        if _STATE.observe_call(name, key := _sig_key(args, kwargs)):
            entry = _STATE.register_signature(
                name, key, _describe_call(args, kwargs)
            )
            if entry is not None:
                if not _STATE.installed():
                    # no monitoring hook: the wrapper itself is the
                    # compile counter (a new jit signature compiles)
                    JIT_COMPILES.labels(fn=name).inc()
                if _cost_enabled():
                    _analyze_cost(name, self._fn, args, kwargs)
        prev = getattr(_tl, "fn", None)
        _tl.fn = name
        try:
            return self._fn(*args, **kwargs)
        finally:
            _tl.fn = prev

    def __getattr__(self, item):
        return getattr(self._fn, item)

    def __repr__(self) -> str:
        return f"<xray.instrument({self._name!r}) of {self._fn!r}>"


def instrument(name: str) -> Callable[[Callable], Callable]:
    """Decorator: ``instrument("als.half")(jax.jit(f))``.  Installing
    the monitoring listeners rides along — by the time an instrumented
    fn exists, the process is a jax process.

    Instrumented seams (grep for ``xray.instrument(`` to re-derive):
    ``als.half_iteration`` / ``als.phase_probe`` / ``als.sharded_half``
    / ``als.sweep_half`` / ``als.expand_sides`` / ``als.sq_err_sum``
    (models/als.py), ``als.fused`` (ops/fused_als.py — BOTH gather
    impls' pallas entries share the name; the impl shows up in the
    signature via the entry fn and its static tile-plan kwargs),
    ``topk.*`` (ops/topk.py), and ``live.foldin_solve``
    (live/foldin.py — a steady fold-in daemon must show one signature
    per padded (B, K) rung, not one per cycle)."""

    def deco(fn: Callable) -> Callable:
        install()
        return _Instrumented(fn, name)

    return deco


# -- device sampling --------------------------------------------------------

_MEM_STATS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")


def _live_bytes_by_device() -> dict:
    """Fallback accounting: sum live jax array bytes per device (the
    CPU backend exposes no allocator stats)."""
    import jax

    out: dict = {}
    for a in jax.live_arrays():
        try:
            shards = getattr(a, "addressable_shards", None)
            if shards:
                for sh in shards:
                    out[sh.device] = (
                        out.get(sh.device, 0) + int(sh.data.nbytes)
                    )
            else:
                d = next(iter(a.devices()))
                out[d] = out.get(d, 0) + int(a.nbytes)
        except Exception:
            continue
    return out


def sample_devices_once() -> list:
    """One sampling pass over ``jax.devices()``; sets the
    ``pio_device_memory_bytes`` gauges and caches the snapshot for
    ``/debug/xray``.  Safe to call from tests and scrape handlers."""
    import jax

    out = []
    live = None
    for d in jax.devices():
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats:
            picked = {
                k: int(stats[k]) for k in _MEM_STATS if k in stats
            }
            source = "memory_stats"
        else:
            if live is None:
                live = _live_bytes_by_device()
            picked = {"live_bytes": int(live.get(d, 0))}
            source = "live_arrays"
        label = f"{d.platform}:{d.id}"
        for stat, v in picked.items():
            DEVICE_MEMORY.labels(device=label, stat=stat).set(float(v))
        out.append({
            "device": label,
            "kind": str(getattr(d, "device_kind", d.platform)),
            "source": source,
            "stats": picked,
        })
    _STATE.set_devices(out)
    return out


def set_sample_period(period_s: float) -> None:
    """Sampler cadence; <= 0 disables future :func:`start_sampler`
    calls (running samplers stop at their next tick)."""
    _STATE.set_sample_period(period_s)
    if period_s <= 0:
        stop_sampler()


def start_sampler(period_s: Optional[float] = None) -> bool:
    """Start the daemon device sampler (idempotent — one per process,
    registered at server/workflow boot like the breaker gauges).
    Returns True when a sampler is running after the call."""
    if period_s is not None:
        _STATE.set_sample_period(period_s)
    stop = _STATE.sampler_slot()
    if stop is None:
        _stop, period = _STATE.sampler_state()
        return period > 0 and _stop is not None and not _stop.is_set()

    def loop():
        while True:
            try:
                sample_devices_once()
            except Exception:
                pass  # a flaky backend must not kill the sampler
            _ignored, period = _STATE.sampler_state()
            if period <= 0 or stop.wait(max(period, 0.05)):
                return

    t = threading.Thread(
        target=loop, name="pio-xray-sampler", daemon=True
    )
    _STATE.set_sampler(t)
    t.start()
    return True


def stop_sampler() -> None:
    stop, _period = _STATE.sampler_state()
    if stop is not None:
        stop.set()
    _STATE.set_sampler(None)


# -- mesh / cache hook ------------------------------------------------------


def note_compilation_cache(cache_dir: Optional[str]) -> None:
    """Called by ``parallel.mesh.enable_compilation_cache`` so the
    /debug/xray payload names the active cache directory."""
    install()
    _STATE.note_cache_dir(cache_dir)


# -- read side --------------------------------------------------------------


def jit_stats() -> dict:
    return _STATE.snapshot()["fns"]


def total_backend_compiles() -> int:
    """Backend compiles booked so far, all fns + untracked — pio-tower
    diffs this per sweep to surface mid-train recompile churn (a sweep
    that recompiled is a sweep whose wall time lies about steady
    state)."""
    snap = _STATE.snapshot()
    return sum(st["backendCompiles"] for st in snap["fns"].values())


def device_high_water() -> Optional[int]:
    """Max bytes across devices from the most recent sample:
    ``peak_bytes_in_use`` where the allocator reports it, else the
    current in-use/live figure — the single high-water number a run
    manifest records per sweep."""
    snap = _STATE.snapshot()
    best: Optional[int] = None
    for s in snap["devices"]:
        stats = s.get("stats") or {}
        v = stats.get("peak_bytes_in_use")
        if v is None:
            v = stats.get("bytes_in_use", stats.get("live_bytes"))
        if v is not None and (best is None or v > best):
            best = int(v)
    return best


def recompile_events() -> list:
    return _STATE.snapshot()["recompiles"]


def xray_payload() -> dict:
    """The ``GET /debug/xray`` document (docs/ARCHITECTURE.md "X-ray"
    lists the schema).  Builds from cached state only — serving a
    scrape never imports jax or touches a device."""
    from .flight import get_flight_recorder

    snap = _STATE.snapshot()
    exemplars = [
        {"le": le, "traceId": ex, "value": v, "at": ts}
        for le, ex, v, ts in _query_latency_exemplars()
    ]
    return {
        "monitoring": {
            "installed": snap["installed"],
            "installError": snap["installError"],
        },
        "jit": snap["fns"],
        "recompiles": snap["recompiles"],
        "compileCache": {
            "dir": snap["cacheDir"],
            "events": snap["cacheEvents"],
        },
        "devices": {
            "sampledAt": snap["devicesSampledAt"],
            "samples": snap["devices"],
        },
        "flight": get_flight_recorder().summary(spans=True),
        "latencyExemplars": exemplars,
    }


def _query_latency_exemplars() -> list:
    from . import QUERY_LATENCY

    return QUERY_LATENCY.child().exemplar_items()
