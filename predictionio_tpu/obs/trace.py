"""Trace propagation + span recording.

A *trace id* names one logical request as it crosses processes: minted
at the serving edge (or supplied by the client via the ``X-PIO-Trace``
header), carried through ``DeliveryQueue`` payload headers to the event
server, and stamped on every span recorded while the id is in scope.
A *span* is one named, timed unit of work (``serve.query``,
``events.write``, ``als.gram``, ``eval.sweep`` ...) with a wall-clock
start timestamp and a monotonic-clock duration.

Spans land in a bounded in-memory ring (cheap, always on — the
dashboard and tests read it) and, when a journal directory is
configured (``--telemetry-dir`` or ``PIO_TPU_TELEMETRY_DIR``), are also
appended as JSON lines to ``<dir>/spans-<pid>.jsonl`` so a slow query
can be grepped by trace id across every involved process after the
fact.

Clock discipline: ``start`` is ``time.time()`` (a timestamp — it must
be comparable across machines), ``duration_s`` comes from
``time.perf_counter()`` deltas (PIO109: wall clocks never measure
durations).
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time
from pathlib import Path
from typing import Iterator, Optional

__all__ = [
    "Span",
    "TRACE_HEADER",
    "Tracer",
    "current_trace_id",
    "new_trace_id",
    "trace_scope",
]

TRACE_HEADER = "X-PIO-Trace"


def new_trace_id() -> str:
    # os.urandom beats uuid4 ~8x and this runs on the serving hot path
    # for every request that didn't bring its own id
    return "t-" + os.urandom(8).hex()


_scope = threading.local()


class trace_scope:
    """Bind ``trace_id`` to this thread for the duration of the block
    (spans recorded inside inherit it).  ``None`` keeps any outer
    scope's id — call sites don't branch.

    A slotted class rather than a generator contextmanager: this wraps
    every served query, and the generator machinery costs ~1.4 us
    against ~0.2 us for plain __enter__/__exit__.
    """

    __slots__ = ("trace_id", "_prev")

    def __init__(self, trace_id: Optional[str]):
        self.trace_id = trace_id

    def __enter__(self) -> Optional[str]:
        self._prev = getattr(_scope, "trace_id", None)
        tid = self.trace_id if self.trace_id is not None else self._prev
        _scope.trace_id = tid
        return tid

    def __exit__(self, *exc) -> None:
        _scope.trace_id = self._prev


def current_trace_id() -> Optional[str]:
    return getattr(_scope, "trace_id", None)


class Span:
    __slots__ = ("name", "trace_id", "start", "duration_s", "attrs")

    def __init__(self, name: str, trace_id: Optional[str], start: float,
                 duration_s: float, attrs: Optional[dict] = None):
        self.name = name
        self.trace_id = trace_id
        self.start = start
        self.duration_s = duration_s
        self.attrs = attrs or {}

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "traceId": self.trace_id,
            "start": self.start,
            "durationSec": self.duration_s,
            "pid": os.getpid(),
            **({"attrs": self.attrs} if self.attrs else {}),
        }


class Tracer:
    """Bounded span ring + optional JSONL journal."""

    def __init__(self, capacity: int = 4096,
                 journal_dir: Optional[Path] = None):
        self._lock = threading.Lock()
        self._ring: collections.deque[Span] = collections.deque(
            maxlen=capacity
        )
        self._journal_dir = Path(journal_dir) if journal_dir else None
        self._journal = None
        self._journal_failed = False
        self.dropped_journal_writes = 0

    # -- configuration -----------------------------------------------------
    def configure(self, journal_dir: Optional[os.PathLike | str]) -> None:
        """(Re)point the JSONL journal; ``None`` disables it."""
        with self._lock:
            if self._journal is not None:
                try:
                    self._journal.close()
                except OSError:
                    pass
            self._journal = None
            self._journal_failed = False
            self._journal_dir = Path(journal_dir) if journal_dir else None

    def journal_path(self) -> Optional[Path]:
        with self._lock:
            d = self._journal_dir
        return d / f"spans-{os.getpid()}.jsonl" if d else None

    def _journal_write(self, span: Span) -> None:
        # lock held by the caller (record); failures disable the
        # journal rather than poisoning the hot path with IO errors
        if self._journal_failed or self._journal_dir is None:
            return
        if self._journal is None:
            try:
                self._journal_dir.mkdir(parents=True, exist_ok=True)
                path = self._journal_dir / f"spans-{os.getpid()}.jsonl"
                self._journal = open(path, "a", encoding="utf-8")
            except OSError:
                self._journal_failed = True
                self.dropped_journal_writes += 1
                return
        try:
            self._journal.write(json.dumps(span.to_json()) + "\n")
            self._journal.flush()
        except (OSError, ValueError):
            self.dropped_journal_writes += 1

    # -- recording ---------------------------------------------------------
    def record(self, name: str, duration_s: float,
               trace_id: Optional[str] = None,
               attrs: Optional[dict] = None,
               start: Optional[float] = None) -> Span:
        """Record an already-measured span.  ``trace_id=None`` takes the
        thread's current scope id (possibly still None — spans outside
        any request are legal)."""
        span = Span(
            name=name,
            trace_id=trace_id if trace_id is not None else current_trace_id(),
            start=start if start is not None else time.time(),
            duration_s=duration_s,
            attrs=attrs,
        )
        with self._lock:
            self._ring.append(span)
            self._journal_write(span)
        return span

    @contextlib.contextmanager
    def span(self, name: str, attrs: Optional[dict] = None,
             trace_id: Optional[str] = None) -> Iterator[dict]:
        """Time the enclosed block and record it.  The yielded dict is
        the span's attrs — callers may add keys mid-flight.  An escaping
        exception still records the span, with ``error`` set."""
        a = dict(attrs or {})
        started = time.time()
        t0 = time.perf_counter()
        try:
            yield a
        except BaseException as e:
            a["error"] = type(e).__name__
            raise
        finally:
            self.record(
                name, time.perf_counter() - t0,
                trace_id=trace_id, attrs=a, start=started,
            )

    # -- reading -----------------------------------------------------------
    def spans(self, trace_id: Optional[str] = None,
              name: Optional[str] = None,
              limit: Optional[int] = None) -> list[Span]:
        """Newest-last snapshot of the ring, optionally filtered."""
        with self._lock:
            out = list(self._ring)
        if trace_id is not None:
            out = [s for s in out if s.trace_id == trace_id]
        if name is not None:
            out = [s for s in out if s.name == name]
        if limit is not None:
            out = out[-limit:]
        return out

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def stats(self) -> dict:
        with self._lock:
            depth = len(self._ring)
            cap = self._ring.maxlen
            journaling = self._journal_dir is not None \
                and not self._journal_failed
            dropped = self.dropped_journal_writes
        return {
            "depth": depth,
            "capacity": cap,
            "journaling": journaling,
            "droppedJournalWrites": dropped,
        }

    def close(self) -> None:
        with self._lock:
            if self._journal is not None:
                try:
                    self._journal.close()
                except OSError:
                    pass
                self._journal = None
