"""Trace propagation + span recording.

A *trace id* names one logical request as it crosses processes: minted
at the serving edge (or supplied by the client via the ``X-PIO-Trace``
header), carried through ``DeliveryQueue`` payload headers to the event
server, and stamped on every span recorded while the id is in scope.
A *span* is one named, timed unit of work (``serve.query``,
``events.write``, ``als.gram``, ``eval.sweep`` ...) with a wall-clock
start timestamp and a monotonic-clock duration.

Spans land in a bounded in-memory ring (cheap, always on — the
dashboard and tests read it) and, when a journal directory is
configured (``--telemetry-dir`` or ``PIO_TPU_TELEMETRY_DIR``), are also
appended as JSON lines to ``<dir>/spans-<pid>.jsonl`` so a slow query
can be grepped by trace id across every involved process after the
fact.

Clock discipline: ``start`` is ``time.time()`` (a timestamp — it must
be comparable across machines), ``duration_s`` comes from
``time.perf_counter()`` deltas (PIO109: wall clocks never measure
durations).
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time
from pathlib import Path
from typing import Iterator, Optional

__all__ = [
    "Span",
    "TRACE_HEADER",
    "Tracer",
    "current_trace_id",
    "new_trace_id",
    "trace_scope",
]

TRACE_HEADER = "X-PIO-Trace"


def new_trace_id() -> str:
    # os.urandom beats uuid4 ~8x and this runs on the serving hot path
    # for every request that didn't bring its own id
    return "t-" + os.urandom(8).hex()


_scope = threading.local()


class trace_scope:
    """Bind ``trace_id`` to this thread for the duration of the block
    (spans recorded inside inherit it).  ``None`` keeps any outer
    scope's id — call sites don't branch.

    A slotted class rather than a generator contextmanager: this wraps
    every served query, and the generator machinery costs ~1.4 us
    against ~0.2 us for plain __enter__/__exit__.
    """

    __slots__ = ("trace_id", "_prev")

    def __init__(self, trace_id: Optional[str]):
        self.trace_id = trace_id

    def __enter__(self) -> Optional[str]:
        self._prev = getattr(_scope, "trace_id", None)
        tid = self.trace_id if self.trace_id is not None else self._prev
        _scope.trace_id = tid
        return tid

    def __exit__(self, *exc) -> None:
        _scope.trace_id = self._prev


def current_trace_id() -> Optional[str]:
    return getattr(_scope, "trace_id", None)


class Span:
    __slots__ = ("name", "trace_id", "start", "duration_s", "attrs")

    def __init__(self, name: str, trace_id: Optional[str], start: float,
                 duration_s: float, attrs: Optional[dict] = None):
        self.name = name
        self.trace_id = trace_id
        self.start = start
        self.duration_s = duration_s
        self.attrs = attrs or {}

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "traceId": self.trace_id,
            "start": self.start,
            "durationSec": self.duration_s,
            "pid": os.getpid(),
            **({"attrs": self.attrs} if self.attrs else {}),
        }


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_worker() -> Optional[int]:
    """The process's cluster worker index, when launched as one
    (``PIO_TPU_PROCESS_INDEX`` — the multihost harness stamps it per
    spawned worker; ``run_train`` also sets it from
    ``jax.process_index()``).  ``None`` in single-process land."""
    v = os.environ.get("PIO_TPU_PROCESS_INDEX")
    if v is None:
        return None
    try:
        return int(v)
    except ValueError:
        return None


class Tracer:
    """Bounded span ring + optional JSONL journal.

    The journal is **rotated**, not unbounded: once the active segment
    exceeds ``max_segment_bytes`` it is renamed to
    ``spans-<pid>.jsonl.1`` (older segments shift to ``.2`` ... and the
    oldest beyond ``keep_segments`` is deleted), and writing continues
    into a fresh active file.  A serving process that stays up for
    weeks therefore holds at most ``(keep_segments + 1) *
    max_segment_bytes`` of journal on disk instead of growing without
    bound.  Env overrides: ``PIO_TPU_TELEMETRY_SEGMENT_BYTES`` /
    ``PIO_TPU_TELEMETRY_KEEP``.
    """

    def __init__(self, capacity: int = 4096,
                 journal_dir: Optional[Path] = None,
                 max_segment_bytes: Optional[int] = None,
                 keep_segments: Optional[int] = None):
        self._lock = threading.Lock()
        self._ring: collections.deque[Span] = collections.deque(
            maxlen=capacity
        )
        self._journal_dir = Path(journal_dir) if journal_dir else None
        self._journal = None
        self._journal_failed = False
        self._journal_bytes = 0
        self._rotations = 0
        self._segment_cap = (
            max_segment_bytes if max_segment_bytes is not None
            else _env_int("PIO_TPU_TELEMETRY_SEGMENT_BYTES", 16 << 20)
        )
        self._keep = (
            keep_segments if keep_segments is not None
            else _env_int("PIO_TPU_TELEMETRY_KEEP", 3)
        )
        self._worker = _env_worker()
        self.dropped_journal_writes = 0

    # -- configuration -----------------------------------------------------
    def configure(self, journal_dir: Optional[os.PathLike | str],
                  max_segment_bytes: Optional[int] = None,
                  keep_segments: Optional[int] = None) -> None:
        """(Re)point the JSONL journal; ``None`` disables it.  The
        rotation knobs keep their current values unless given."""
        with self._lock:
            if self._journal is not None:
                try:
                    self._journal.close()
                except OSError:
                    pass
            self._journal = None
            self._journal_failed = False
            self._journal_bytes = 0
            self._journal_dir = Path(journal_dir) if journal_dir else None
            if max_segment_bytes is not None:
                self._segment_cap = max_segment_bytes
            if keep_segments is not None:
                self._keep = keep_segments

    def set_process_index(self, worker: Optional[int]) -> None:
        """Stamp this process's cluster worker index into the journal
        filename (``spans-w<k>-<pid>.jsonl``) and every span record —
        a cluster run's journals merge and grep by worker instead of
        by opaque pid.  An open journal is closed so the next write
        reopens under the stamped name."""
        with self._lock:
            self._worker = worker
            if self._journal is not None:
                try:
                    self._journal.close()
                except OSError:
                    pass
                self._journal = None
                self._journal_bytes = 0

    def _journal_name(self) -> str:
        if self._worker is not None:
            return f"spans-w{self._worker}-{os.getpid()}.jsonl"
        return f"spans-{os.getpid()}.jsonl"

    def journal_path(self) -> Optional[Path]:
        with self._lock:
            d = self._journal_dir
            name = self._journal_name()
        return d / name if d else None

    def _journal_write(self, span: Span) -> None:
        # lock held by the caller (record); failures disable the
        # journal rather than poisoning the hot path with IO errors
        if self._journal_failed or self._journal_dir is None:
            return
        if self._journal is None:
            try:
                self._journal_dir.mkdir(parents=True, exist_ok=True)
                path = self._journal_dir / self._journal_name()
                self._journal = open(path, "a", encoding="utf-8")
                try:
                    self._journal_bytes = path.stat().st_size
                except OSError:
                    self._journal_bytes = 0
            except OSError:
                self._journal_failed = True
                self.dropped_journal_writes += 1
                return
        try:
            doc = span.to_json()
            if self._worker is not None:
                doc["worker"] = self._worker
            line = json.dumps(doc) + "\n"
            self._journal.write(line)
            self._journal.flush()
            self._journal_bytes += len(line)
        except (OSError, ValueError):
            self.dropped_journal_writes += 1
            return
        if self._segment_cap and self._journal_bytes >= self._segment_cap:
            self._rotate_locked()

    def _rotate_locked(self) -> None:
        """Shift the segment chain and start a fresh active file.
        Caller holds ``self._lock``.  Rotation failures disable the
        journal (same contract as open failures) — they must never
        raise into ``record`` on the serving path."""
        try:
            self._journal.close()
        except OSError:
            pass
        self._journal = None
        self._journal_bytes = 0
        base = self._journal_dir / self._journal_name()
        try:
            oldest = base.with_name(base.name + f".{self._keep}")
            if self._keep <= 0:
                # keep-0: the capped active segment is simply discarded
                base.unlink(missing_ok=True)
            else:
                oldest.unlink(missing_ok=True)
                for k in range(self._keep - 1, 0, -1):
                    seg = base.with_name(base.name + f".{k}")
                    if seg.exists():
                        seg.rename(base.with_name(base.name + f".{k + 1}"))
                base.rename(base.with_name(base.name + ".1"))
            self._rotations += 1
        except OSError:
            self._journal_failed = True

    # -- recording ---------------------------------------------------------
    def record(self, name: str, duration_s: float,
               trace_id: Optional[str] = None,
               attrs: Optional[dict] = None,
               start: Optional[float] = None) -> Span:
        """Record an already-measured span.  ``trace_id=None`` takes the
        thread's current scope id (possibly still None — spans outside
        any request are legal)."""
        span = Span(
            name=name,
            trace_id=trace_id if trace_id is not None else current_trace_id(),
            start=start if start is not None else time.time(),
            duration_s=duration_s,
            attrs=attrs,
        )
        with self._lock:
            self._ring.append(span)
            self._journal_write(span)
        return span

    @contextlib.contextmanager
    def span(self, name: str, attrs: Optional[dict] = None,
             trace_id: Optional[str] = None) -> Iterator[dict]:
        """Time the enclosed block and record it.  The yielded dict is
        the span's attrs — callers may add keys mid-flight.  An escaping
        exception still records the span, with ``error`` set."""
        a = dict(attrs or {})
        started = time.time()
        t0 = time.perf_counter()
        try:
            yield a
        except BaseException as e:
            a["error"] = type(e).__name__
            raise
        finally:
            self.record(
                name, time.perf_counter() - t0,
                trace_id=trace_id, attrs=a, start=started,
            )

    # -- reading -----------------------------------------------------------
    def spans(self, trace_id: Optional[str] = None,
              name: Optional[str] = None,
              limit: Optional[int] = None) -> list[Span]:
        """Newest-last snapshot of the ring, optionally filtered."""
        with self._lock:
            out = list(self._ring)
        if trace_id is not None:
            out = [s for s in out if s.trace_id == trace_id]
        if name is not None:
            out = [s for s in out if s.name == name]
        if limit is not None:
            out = out[-limit:]
        return out

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def stats(self) -> dict:
        with self._lock:
            depth = len(self._ring)
            cap = self._ring.maxlen
            journaling = self._journal_dir is not None \
                and not self._journal_failed
            dropped = self.dropped_journal_writes
            seg_bytes = self._journal_bytes
            seg_cap = self._segment_cap
            keep = self._keep
            rotations = self._rotations
        return {
            "depth": depth,
            "capacity": cap,
            "journaling": journaling,
            "droppedJournalWrites": dropped,
            "segmentBytes": seg_bytes,
            "segmentCapBytes": seg_cap,
            "keepSegments": keep,
            "rotations": rotations,
        }

    def close(self) -> None:
        with self._lock:
            if self._journal is not None:
                try:
                    self._journal.close()
                except OSError:
                    pass
                self._journal = None
