"""Training run manifests: a crash-tolerant JSONL journal per run.

`pio train` inherited the reference's blind spot: a Spark batch job
whose only observable surface was the driver log.  The manifest closes
it — every training (and evaluation) run writes a structured journal
under ``$PIO_TPU_HOME/telemetry/runs/<instance_id>/run.jsonl`` that
answers, during OR after the run, "which sweep was slow, in which
phase, on which worker, and did the loss move?".

File contract (the crash-tolerance story):

* The **header** record is written ATOMICALLY (tmp file + rename), so
  a manifest either exists with a valid header or not at all — a crash
  during creation cannot leave a torn first line.
* Every subsequent record (``sweep`` / ``event`` / ``candidate`` /
  ``final``) is ONE appended, flushed JSON line.  A crash mid-append
  tears at most the LAST line; :func:`read_manifest` drops an
  unparsable trailing line and keeps everything before it (the
  ``StepCheckpointer`` torn-newest-step contract, applied to
  telemetry).
* A manifest without a ``final`` record is a **live** run (training in
  flight, or a crash — ``header.pid`` + mtime disambiguate for a
  human; the console renders both as "live/stale").

Record kinds (the schema table lives in docs/ARCHITECTURE.md "Tower"):

``header``     run identity: instance id, kind (train/eval), planned
               sweeps, worker count, config summary, start timestamp.
``sweep``      one training sweep: 1-based index, wall seconds, the
               per-phase decomposition (``phases`` — seconds by phase
               name, summing to ~the sweep wall), optional training
               loss (RMSE), device-memory high-water bytes,
               compile-count delta, shard events drained this sweep.
``event``      an out-of-band anomaly (shard degradation, watchdog
               warnings) with its own timestamp.
``candidate``  one evaluation-sweep candidate's score (eval runs).
``metrics``    a merged cluster registry snapshot (multi-worker runs;
               worker 0 appends one at finalize).
``final``      terminal status (completed/aborted/failed), totals,
               phase sums, abort reason.

Pure stdlib and jax-free (the pio-obs contract): readable from the
dashboard, the CLI (``tools/runlog.py``), and tests without touching a
device.
"""

from __future__ import annotations

import json
import os
import threading
import time
from pathlib import Path
from typing import Optional

__all__ = [
    "RunManifest",
    "list_runs",
    "read_manifest",
    "runs_root",
    "summarize",
    "diff_runs",
]

MANIFEST_NAME = "run.jsonl"


def runs_root(root: Optional[os.PathLike | str] = None) -> Path:
    """The manifest tree: ``$PIO_TPU_HOME/telemetry/runs`` (overridable
    for tests via the explicit argument or ``PIO_TPU_RUNLOG_DIR``)."""
    if root is not None:
        return Path(root)
    env = os.environ.get("PIO_TPU_RUNLOG_DIR")
    if env:
        return Path(env)
    from . import telemetry_home

    return telemetry_home() / "runs"


class RunManifest:
    """Writer for one run's journal.  Thread-safe; every append is one
    flushed line, so concurrent sweep/event writers interleave whole
    records (the GIL serializes single ``write`` calls and the file is
    opened in append mode)."""

    def __init__(self, instance_id: str, kind: str = "train",
                 meta: Optional[dict] = None,
                 root: Optional[os.PathLike | str] = None):
        self.instance_id = instance_id
        self.kind = kind
        self.dir = runs_root(root) / instance_id
        self.path = self.dir / MANIFEST_NAME
        self._lock = threading.Lock()
        self._file = None
        self._failed = False
        self.finalized = False
        header = {
            "kind": "header",
            "instanceId": instance_id,
            "runKind": kind,
            "start": time.time(),
            "pid": os.getpid(),
            **(meta or {}),
        }
        try:
            self.dir.mkdir(parents=True, exist_ok=True)
            tmp = self.dir / (MANIFEST_NAME + ".tmp")
            tmp.write_text(json.dumps(header) + "\n", encoding="utf-8")
            tmp.rename(self.path)  # atomic: header line is all-or-nothing
            self._file = open(self.path, "a", encoding="utf-8")
        except OSError:
            # telemetry must never fail a training run: a manifest that
            # cannot be written degrades to a no-op writer
            self._failed = True

    # -- writing -----------------------------------------------------------
    def append(self, record: dict) -> None:
        line = json.dumps(record) + "\n"
        with self._lock:
            if self._failed or self._file is None:
                return
            try:
                self._file.write(line)
                self._file.flush()
            except (OSError, ValueError):
                self._failed = True

    def sweep(self, index: int, seconds: float, phases: dict,
              **extra) -> None:
        """One training sweep (1-based ``index``).  ``phases`` maps
        phase name -> seconds and should sum to ~``seconds``."""
        self.append({
            "kind": "sweep",
            "i": index,
            "at": time.time(),
            "seconds": seconds,
            "phases": {k: round(v, 6) for k, v in phases.items()},
            **extra,
        })

    def event(self, event: str, **fields) -> None:
        self.append({
            "kind": "event", "event": event, "at": time.time(), **fields,
        })

    def candidate(self, index: int, **fields) -> None:
        """One evaluation candidate's outcome (eval runs)."""
        self.append({
            "kind": "candidate", "i": index, "at": time.time(), **fields,
        })

    def metrics(self, merged_state: dict, workers: list) -> None:
        """A merged cluster-registry snapshot (multi-worker runs)."""
        self.append({
            "kind": "metrics", "at": time.time(),
            "workers": workers, "state": merged_state,
        })

    def finalize(self, status: str, **fields) -> None:
        """Append the terminal record and close.  Idempotent — only the
        first call writes (an abort path and a generic error path may
        both try)."""
        with self._lock:
            if self.finalized:
                return
            self.finalized = True
        self.append({
            "kind": "final", "status": status, "at": time.time(), **fields,
        })
        self.close()

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None


# -- reading ----------------------------------------------------------------


def read_manifest(path: os.PathLike | str) -> Optional[dict]:
    """Parse one manifest (a run dir or the ``run.jsonl`` itself) into
    ``{"header", "sweeps", "events", "candidates", "metrics", "final",
    "live", "path"}``.  Torn trailing line (crash mid-append) is
    dropped; a torn line ANYWHERE else is skipped too (never happens
    under the writer contract, but a reader must not die on it).
    Returns None when there is no valid header."""
    p = Path(path)
    if p.is_dir():
        p = p / MANIFEST_NAME
    try:
        lines = p.read_text(encoding="utf-8").splitlines()
    except OSError:
        return None
    records = []
    for ln in lines:
        ln = ln.strip()
        if not ln:
            continue
        try:
            records.append(json.loads(ln))
        except json.JSONDecodeError:
            continue
    if not records or records[0].get("kind") != "header":
        return None
    out = {
        "header": records[0],
        "sweeps": [r for r in records if r.get("kind") == "sweep"],
        "events": [r for r in records if r.get("kind") == "event"],
        "candidates": [r for r in records if r.get("kind") == "candidate"],
        "metrics": [r for r in records if r.get("kind") == "metrics"],
        "final": next(
            (r for r in records if r.get("kind") == "final"), None
        ),
        "path": str(p),
    }
    out["live"] = out["final"] is None
    return out


def list_runs(root: Optional[os.PathLike | str] = None,
              limit: Optional[int] = None) -> list:
    """Parsed manifests under the runs root, newest header first."""
    base = runs_root(root)
    views = []
    try:
        dirs = [d for d in base.iterdir() if d.is_dir()]
    except OSError:
        return []
    for d in dirs:
        v = read_manifest(d)
        if v is not None:
            views.append(v)
    views.sort(key=lambda v: v["header"].get("start", 0.0), reverse=True)
    return views[:limit] if limit else views


# -- derived views -----------------------------------------------------------


def phase_totals(view: dict) -> dict:
    """Seconds per phase summed over all sweep records."""
    out: dict[str, float] = {}
    for s in view["sweeps"]:
        for k, v in (s.get("phases") or {}).items():
            out[k] = out.get(k, 0.0) + float(v)
    return out


def summarize(view: dict) -> dict:
    """One run's triage card: counts, totals, per-phase sums, slowest
    sweep, loss trajectory endpoints — what ``tools/runlog.py
    summarize`` prints and ``/train.html`` renders per row."""
    sweeps = view["sweeps"]
    seconds = [float(s.get("seconds", 0.0)) for s in sweeps]
    losses = [
        (s["i"], s["loss"]) for s in sweeps
        if s.get("loss") is not None
    ]
    slowest = None
    if sweeps:
        worst = max(sweeps, key=lambda s: float(s.get("seconds", 0.0)))
        slowest = {"i": worst["i"], "seconds": worst.get("seconds")}
    final = view["final"] or {}
    hdr = view["header"]
    planned = hdr.get("sweepsPlanned")
    if planned is None:
        # the trainer declares its budget after the header is written
        planned = next(
            (e.get("sweepsPlanned") for e in view["events"]
             if e.get("event") == "plan"), None,
        )
    return {
        "instanceId": hdr.get("instanceId"),
        "runKind": hdr.get("runKind"),
        "start": hdr.get("start"),
        "live": view["live"],
        "status": final.get("status", "live"),
        "reason": final.get("reason"),
        "sweeps": len(sweeps),
        "sweepsPlanned": planned,
        "sweepSecondsTotal": round(sum(seconds), 6),
        "sweepSecondsMean": (
            round(sum(seconds) / len(seconds), 6) if seconds else None
        ),
        "phaseTotals": {
            k: round(v, 6) for k, v in sorted(phase_totals(view).items())
        },
        "slowestSweep": slowest,
        "firstLoss": losses[0][1] if losses else None,
        "lastLoss": losses[-1][1] if losses else None,
        "events": sum(
            1 for e in view["events"] if e.get("event") != "plan"
        ),
        "candidates": len(view["candidates"]),
        "workers": hdr.get("workers"),
        "wallSeconds": final.get("wallSeconds"),
    }


def diff_runs(a: dict, b: dict) -> dict:
    """Phase-level A/B between two runs — the regression-triage view:
    per-phase total and per-sweep mean for each run plus the B/A
    ratio, ordered by how much absolute time the phase gained."""

    def per_sweep(view):
        n = max(len(view["sweeps"]), 1)
        return {k: v / n for k, v in phase_totals(view).items()}

    pa, pb = per_sweep(a), per_sweep(b)
    rows = []
    for phase in sorted(set(pa) | set(pb)):
        va, vb = pa.get(phase, 0.0), pb.get(phase, 0.0)
        rows.append({
            "phase": phase,
            "aMeanSeconds": round(va, 6),
            "bMeanSeconds": round(vb, 6),
            "deltaSeconds": round(vb - va, 6),
            "ratio": round(vb / va, 4) if va > 0 else None,
        })
    rows.sort(key=lambda r: -abs(r["deltaSeconds"]))
    sa, sb = summarize(a), summarize(b)
    return {
        "a": {"instanceId": sa["instanceId"], "sweeps": sa["sweeps"],
              "sweepSecondsMean": sa["sweepSecondsMean"]},
        "b": {"instanceId": sb["instanceId"], "sweeps": sb["sweeps"],
              "sweepSecondsMean": sb["sweepSecondsMean"]},
        "sweepMeanRatio": (
            round(sb["sweepSecondsMean"] / sa["sweepSecondsMean"], 4)
            if sa["sweepSecondsMean"] and sb["sweepSecondsMean"] else None
        ),
        "phases": rows,
    }
