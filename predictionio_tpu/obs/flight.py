"""Slow-query flight recorder: a bounded worst-N ring of span trees.

The latency histogram says *that* the p99 moved; the flight recorder
says *why*, for the concrete requests that moved it.  Every finished
request is **offered** with its trace id and duration; the recorder
keeps the N slowest offers seen so far, and for each admitted request
captures its full span tree (every span the tracer ring holds for that
trace id — ``serve.query``, ``als.*`` probes, ``events.write`` on the
feedback hop, ...) at admission time, before the bounded ring can
evict them.

Hot-path discipline: the common case (request not among the worst N) is
one lock acquisition and one float compare.  The span-tree capture — an
O(ring) scan — happens only for admitted requests, which are by
definition the slow ones; amortized cost on a healthy p50 is nil.

Admission is exact under concurrency: the cheap pre-check may race, but
every candidate that passes it re-enters the lock, is pushed, and the
heap is trimmed back to capacity — so the final contents are always
exactly the N largest durations ever offered (a request rejected by a
stale pre-check had ``duration <= min(heap)`` at that moment, and the
heap minimum only grows).

The exemplar trace ids the latency histogram carries (registry.py) are
the cross-link: ``/metrics`` names a trace id, the flight record holds
its span tree, the JSONL journal holds the cross-process copy.
"""

from __future__ import annotations

import heapq
import os
import threading
import time
from typing import Optional

__all__ = ["FlightRecorder", "get_flight_recorder"]


def _env_capacity() -> int:
    try:
        return max(1, int(os.environ.get("PIO_TPU_XRAY_FLIGHT_N", 16)))
    except ValueError:
        return 16


class FlightRecorder:
    """Keep the worst-``capacity`` offered requests with span trees."""

    def __init__(self, capacity: Optional[int] = None):
        self._lock = threading.Lock()
        self._capacity = capacity if capacity else _env_capacity()
        # min-heap of (duration_s, seq, record); seq breaks duration
        # ties so dict records never get compared
        self._heap: list = []
        self._seq = 0
        self._offers = 0
        self._admissions = 0

    # -- configuration -----------------------------------------------------
    def set_capacity(self, capacity: int) -> None:
        if capacity < 1:
            raise ValueError("flight recorder capacity must be >= 1")
        with self._lock:
            self._capacity = capacity
            while len(self._heap) > capacity:
                heapq.heappop(self._heap)

    # -- recording ---------------------------------------------------------
    def offer(self, trace_id: Optional[str], duration_s: float,
              name: str = "serve.query",
              attrs: Optional[dict] = None, tracer=None) -> bool:
        """Offer one finished request; returns True when admitted.

        ``trace_id=None`` requests are counted but never admitted —
        without an id there is no span tree to key."""
        with self._lock:
            self._offers += 1
            if trace_id is None:
                return False
            if (
                len(self._heap) >= self._capacity
                and duration_s <= self._heap[0][0]
            ):
                return False
        # capture OUTSIDE the lock: the tracer has its own lock, and
        # holding ours across it would couple the two hot paths
        if tracer is None:
            from . import get_tracer

            tracer = get_tracer()
        spans = [s.to_json() for s in tracer.spans(trace_id=trace_id)]
        # pio-scope join: the dominant CPU stacks sampled during this
        # request's wall window — "what was the process doing while
        # this request was slow".  Offers arrive at request end, so
        # the window is [now - duration, now]; the profiler widens it
        # to covering 1 s ring buckets.  Admitted requests only — an
        # O(ring) read has no place on the healthy p50 path.
        now = time.time()
        stacks = None
        try:
            from . import scope as _scope

            if _scope.profiler_running():
                stacks = _scope.get_profiler().dominant_stacks(
                    now - duration_s, now
                )
        except Exception:
            stacks = None  # a profiler hiccup must not drop the record
        record = {
            "traceId": trace_id,
            "name": name,
            "durationSec": duration_s,
            "at": now,
            "spanCount": len(spans),
            "spans": spans,
            **({"dominantStacks": stacks} if stacks else {}),
            **({"attrs": attrs} if attrs else {}),
        }
        with self._lock:
            if (
                len(self._heap) >= self._capacity
                and duration_s <= self._heap[0][0]
            ):
                return False  # a concurrent slower request won the slot
            self._seq += 1
            heapq.heappush(self._heap, (duration_s, self._seq, record))
            while len(self._heap) > self._capacity:
                heapq.heappop(self._heap)
            self._admissions += 1
        return True

    def annotate(self, trace_id: str, extra: dict) -> bool:
        """Merge ``extra`` into an admitted record's attrs after the
        fact (pio-lens: the router caches a replica's lazily-fetched
        ``segmentsMs`` into its own worst-N entry so the second
        ``/debug/fleet`` read costs no replica round trip).  Returns
        False when the record was never admitted or already evicted."""
        with self._lock:
            for _, _, r in self._heap:
                if r["traceId"] == trace_id:
                    r.setdefault("attrs", {}).update(extra)
                    return True
        return False

    # -- reading -----------------------------------------------------------
    def records(self) -> list:
        """Full flight records, slowest first."""
        with self._lock:
            snap = list(self._heap)
        return [r for _, _, r in sorted(snap, reverse=True)]

    def record_for(self, trace_id: str) -> Optional[dict]:
        for r in self.records():
            if r["traceId"] == trace_id:
                return r
        return None

    def summary(self, spans: bool = False) -> dict:
        """Status-JSON-sized view; ``spans=True`` inlines the trees
        (the /debug/xray payload wants them, /status does not)."""
        with self._lock:
            snap = list(self._heap)
            offers = self._offers
            admissions = self._admissions
            capacity = self._capacity
        worst = []
        for _, _, r in sorted(snap, reverse=True):
            item = {k: r[k] for k in
                    ("traceId", "name", "durationSec", "at", "spanCount")}
            if "dominantStacks" in r:
                # the pio-scope join travels with the summary: a
                # worst-N line names its hot stacks without a second
                # round trip for the full record
                item["dominantStacks"] = r["dominantStacks"]
            if "attrs" in r:
                # capture-time context (pulse segment decomposition,
                # pio-live modelFreshnessSec/foldinSeq): a worst-N line
                # on /status explains itself without the span tree
                item["attrs"] = r["attrs"]
            if spans:
                item["spans"] = r["spans"]
            worst.append(item)
        return {
            "capacity": capacity,
            "offers": offers,
            "admissions": admissions,
            "worst": worst,
        }

    def clear(self) -> None:
        with self._lock:
            self._heap = []
            self._offers = 0
            self._admissions = 0


_flight = FlightRecorder()


def get_flight_recorder() -> FlightRecorder:
    return _flight
