"""pio-tower: cluster-aggregated training observability.

The third leg of the observability stack (serving: pulse, compiler:
xray, **training: tower**).  Training was the one distributed workload
with no aggregation and no persistence — N worker processes each held
an isolated metrics registry and the only record of a 135 s TPU train
was the driver log.  This module owns:

* **The run session** — :class:`TowerSession`: created by
  ``workflow.train.run_train`` / ``workflow.evaluate.run_evaluation``
  around one run, it writes the persistent run manifest
  (:mod:`.runlog`), keeps the live progress snapshot that
  ``GET /debug/train`` serves (sweep i/N, phase split, ETA, loss,
  per-shard lag), and drives the convergence watchdog.  The ALS sweep
  loop reports into whatever session is active via
  :func:`record_sweep` — models/ code never imports workflow/ code.
* **The convergence watchdog** — :class:`Watchdog`: NaN/Inf factors,
  loss divergence over a sliding window, and a stalled-sweep
  wall-clock limit each convert a doomed train into a LOUD typed
  :class:`ConvergenceError` with the manifest finalized and
  ``pio_train_aborts_total{reason}`` booked — instead of 20 more
  sweeps of garbage followed by a confusing save.
* **The cluster aggregator** — :class:`RegistryPublisher` /
  :class:`ClusterAggregator`: in multi-process runs every worker
  serializes its registry snapshot into the coordination dir each
  sweep (atomic tmp+rename, the multihost-harness rendezvous
  contract); worker 0 merges them — counters sum, gauges gain a
  ``{worker}`` label, histograms add bucket-wise
  (:func:`..registry.merge_states`) — into its own ``/metrics``
  (via :func:`obs.set_cluster_renderer`) and into the manifest.  A
  worker that dies mid-run leaves its last published snapshot
  standing, so the aggregate stays consistent.

Always-on sweep telemetry (``pio_train_sweeps_total``,
``pio_train_last_sweep_seconds``, per-phase sweep-granularity times)
is booked here too, session or not — a bare ``ALSTrainer.run`` in a
notebook still shows up on ``/metrics``.

Jax-free at module level (the pio-obs contract); device touches
(memory high-water sampling) import lazily and never raise into the
sweep loop.
"""

from __future__ import annotations

import math
import os
import threading
import time
from pathlib import Path
from typing import Optional

from . import get_registry, log_buckets, set_cluster_renderer
from .registry import merge_states, render_state
from .runlog import RunManifest, list_runs, summarize

__all__ = [
    "ClusterAggregator",
    "ConvergenceError",
    "RegistryPublisher",
    "TowerSession",
    "Watchdog",
    "active_session",
    "note_shard_event",
    "record_candidate",
    "record_sweep",
    "train_payload",
]

_registry = get_registry()

TRAIN_SWEEPS_TOTAL = _registry.counter(
    "pio_train_sweeps_total",
    "Completed ALS training sweeps (one user half + one item half)",
)
TRAIN_LAST_SWEEP_SECONDS = _registry.gauge(
    "pio_train_last_sweep_seconds",
    "Wall seconds of the most recent completed training sweep",
)
TRAIN_ABORTS_TOTAL = _registry.counter(
    "pio_train_aborts_total",
    "Training runs aborted by the convergence watchdog, by reason "
    "(nan_factors/nan_loss/divergence/stalled_sweep)",
    labels=("reason",),
)
TRAIN_LOSS = _registry.gauge(
    "pio_train_loss",
    "Most recent per-sweep training loss (RMSE over the staged COO)",
)
TOWER_PUBLISHES_TOTAL = _registry.counter(
    "pio_tower_publishes_total",
    "Per-sweep registry snapshots published into the coordination dir "
    "(multi-worker runs)",
)
TRAIN_SWEEP_SECONDS = _registry.histogram(
    "pio_train_sweep_seconds",
    "Training sweep wall time (always-on, sweep granularity)",
    buckets=log_buckets(1e-3, 10000.0, per_decade=4),
)

TRAIN_SWEEPS_TOTAL.child()
TRAIN_LAST_SWEEP_SECONDS.child()
TRAIN_LOSS.child()
TRAIN_SWEEP_SECONDS.child()


class ConvergenceError(RuntimeError):
    """Typed watchdog abort.  ``reason`` is machine-readable (it labels
    ``pio_train_aborts_total`` and the manifest's final record)."""

    def __init__(self, reason: str, message: str):
        super().__init__(message)
        self.reason = reason


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class Watchdog:
    """Convergence checks the sweep loop runs after every sweep.

    * ``nan_check`` — a non-finite factor table (or loss) aborts with
      reason ``nan_factors`` / ``nan_loss``; the finiteness flag is
      computed by the trainer (this module stays jax-free).
    * ``divergence`` — loss strictly increasing across
      ``divergence_window`` consecutive observations AND the window's
      last/first ratio >= ``divergence_ratio`` aborts with reason
      ``divergence`` (a λ too small blows up smoothly, not via NaN).
    * ``stall`` — one sweep's wall time above ``stall_limit_s`` aborts
      with reason ``stalled_sweep`` (default off; env
      ``PIO_TPU_TOWER_STALL_S`` arms it fleet-wide).
    """

    def __init__(self, nan_check: bool = True,
                 divergence_window: int = 5,
                 divergence_ratio: float = 2.0,
                 stall_limit_s: Optional[float] = None):
        if divergence_window < 2:
            raise ValueError("divergence_window must be >= 2")
        self.nan_check = nan_check
        self.divergence_window = divergence_window
        self.divergence_ratio = divergence_ratio
        self.stall_limit_s = (
            stall_limit_s if stall_limit_s is not None
            else _env_float("PIO_TPU_TOWER_STALL_S", 0.0)
        )
        self._losses: list[float] = []

    def reset_losses(self) -> None:
        """New model, new window — an eval session trains one model
        per candidate, and losses across candidates must not form a
        fake divergence ramp."""
        self._losses = []

    def check(self, sweep_index: int, seconds: float,
              loss: Optional[float], factors_finite: bool) -> None:
        if self.nan_check and not factors_finite:
            raise ConvergenceError(
                "nan_factors",
                f"sweep {sweep_index}: factor tables contain NaN/Inf — "
                "aborting instead of iterating on garbage",
            )
        if loss is not None:
            if not math.isfinite(loss):
                raise ConvergenceError(
                    "nan_loss",
                    f"sweep {sweep_index}: training loss is {loss}",
                )
            self._losses.append(float(loss))
            w = self.divergence_window
            if len(self._losses) >= w:
                tail = self._losses[-w:]
                increasing = all(
                    b > a for a, b in zip(tail, tail[1:])
                )
                if increasing and tail[-1] >= tail[0] * self.divergence_ratio:
                    raise ConvergenceError(
                        "divergence",
                        f"sweep {sweep_index}: loss rose "
                        f"{w} sweeps in a row "
                        f"({tail[0]:.4g} -> {tail[-1]:.4g}, "
                        f">= {self.divergence_ratio}x) — diverging",
                    )
        if self.stall_limit_s and seconds > self.stall_limit_s:
            raise ConvergenceError(
                "stalled_sweep",
                f"sweep {sweep_index} took {seconds:.1f}s "
                f"(limit {self.stall_limit_s:.1f}s) — stalled",
            )


# -- cluster aggregation -----------------------------------------------------

_SNAP_PREFIX = "tower-metrics-w"


class RegistryPublisher:
    """One worker's side of the aggregation: serialize the process
    registry into the coordination dir, atomically (tmp + rename —
    the same publish discipline the harness's coordinator rendezvous
    and the sharded-COO exchange use), once per sweep."""

    def __init__(self, coord_dir: os.PathLike | str, worker: int,
                 registry=None):
        self.dir = Path(coord_dir)
        self.worker = int(worker)
        self._registry = registry or get_registry()
        self._seq = 0
        self.path = self.dir / f"{_SNAP_PREFIX}{self.worker}.json"

    def publish(self) -> None:
        import json

        self._seq += 1
        doc = {
            "worker": self.worker,
            "seq": self._seq,
            "at": time.time(),
            "pid": os.getpid(),
            "state": self._registry.dump_state(),
        }
        try:
            self.dir.mkdir(parents=True, exist_ok=True)
            tmp = self.path.with_suffix(f".tmp{os.getpid()}")
            tmp.write_text(json.dumps(doc), encoding="utf-8")
            tmp.rename(self.path)
            TOWER_PUBLISHES_TOTAL.child().inc()
        except OSError:
            pass  # telemetry publish must never fail the sweep


class ClusterAggregator:
    """Worker 0's side: read every worker's newest snapshot and merge
    it with the LIVE local registry (worker 0's own numbers are always
    current; its published file exists only so an external merger could
    read all N).  Snapshots are cumulative, so a worker that stops
    publishing (died) contributes its last state — the aggregate never
    goes backwards and never loses a dead worker's counts."""

    def __init__(self, coord_dir: os.PathLike | str,
                 local_worker: int = 0, registry=None):
        self.dir = Path(coord_dir)
        self.local_worker = int(local_worker)
        self._registry = registry or get_registry()
        self._cache: dict[int, dict] = {}

    def _read_snapshots(self) -> dict[int, dict]:
        import json

        try:
            files = sorted(self.dir.glob(f"{_SNAP_PREFIX}*.json"))
        except OSError:
            files = []
        for f in files:
            try:
                w = int(f.stem[len(_SNAP_PREFIX):])
            except ValueError:
                continue
            if w == self.local_worker:
                continue
            try:
                doc = json.loads(f.read_text(encoding="utf-8"))
                self._cache[w] = doc
            except (OSError, ValueError):
                continue  # torn/unreadable: keep the cached snapshot
        return dict(self._cache)

    def workers_seen(self) -> list:
        snaps = self._read_snapshots()
        return sorted({self.local_worker, *snaps})

    def merged_state(self) -> dict:
        snaps = self._read_snapshots()
        tagged = [(self.local_worker, self._registry.dump_state())]
        tagged += [
            (w, snaps[w]["state"]) for w in sorted(snaps)
        ]
        return merge_states(tagged)

    def render(self) -> str:
        return render_state(self.merged_state())


# -- the run session ---------------------------------------------------------

_active_lock = threading.Lock()
_active: Optional["TowerSession"] = None


def active_session() -> Optional["TowerSession"]:
    return _active


def _set_active(session: Optional["TowerSession"]) -> None:
    global _active
    with _active_lock:
        _active = session


class TowerSession:
    """Observability lifecycle of ONE training/evaluation run.

    Chief (worker 0) owns the manifest; every worker of a multi-worker
    run publishes registry snapshots; the chief additionally installs
    the cluster renderer so its ``/metrics`` shows cluster-wide sums
    while the run is live.  Use as::

        session = TowerSession(iid, sweeps_planned=cfg.num_iterations)
        session.start()
        try:
            ...   # sweep loop calls tower.record_sweep(...)
            session.finalize("completed")
        except BaseException as e:
            session.finalize_error(e)
            raise
    """

    def __init__(self, instance_id: str, kind: str = "train",
                 meta: Optional[dict] = None,
                 sweeps_planned: Optional[int] = None,
                 worker: int = 0, n_workers: int = 1,
                 coord_dir: Optional[os.PathLike | str] = None,
                 watchdog: Optional[Watchdog] = None,
                 manifest_root: Optional[os.PathLike | str] = None,
                 loss_value=None):
        self.instance_id = instance_id
        self.kind = kind
        self.worker = int(worker)
        self.n_workers = int(n_workers)
        self.chief = self.worker == 0
        self.watchdog = watchdog if watchdog is not None else Watchdog()
        self.sweeps_planned = sweeps_planned
        self.loss_value = loss_value  # tests inspect the knob
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._start_wall = time.time()
        self._sweep = 0
        self._phase_totals: dict[str, float] = {}
        self._sweep_seconds_total = 0.0
        self._loss_history: list[tuple[int, float]] = []
        self._pending_events: list[dict] = []
        self._events_total = 0
        self._last_sweep: Optional[dict] = None
        self._watch_source = None
        self._first_sweep_start: Optional[float] = None
        self._last_sweep_end: Optional[float] = None
        self._train_run_seconds: Optional[float] = None
        self._train_run_end: Optional[float] = None
        self._finalized = False
        self._compile_base = _compile_total()
        self.manifest: Optional[RunManifest] = None
        if self.chief:
            self.manifest = RunManifest(
                instance_id, kind=kind, root=manifest_root,
                meta={
                    "sweepsPlanned": sweeps_planned,
                    "workers": self.n_workers,
                    **(meta or {}),
                },
            )
        self._publisher: Optional[RegistryPublisher] = None
        self._aggregator: Optional[ClusterAggregator] = None
        if coord_dir is not None and self.n_workers > 1:
            self._publisher = RegistryPublisher(coord_dir, self.worker)
            if self.chief:
                self._aggregator = ClusterAggregator(
                    coord_dir, local_worker=self.worker,
                )

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "TowerSession":
        _set_active(self)
        if self._aggregator is not None:
            set_cluster_renderer(self._aggregator.render)
        return self

    def set_sweeps_planned(self, n: int) -> None:
        """The trainer declares the sweep budget once it knows it (the
        workflow layer can't — iteration counts are algorithm params).
        The header was already written, so the plan lands as an event
        record; readers fall back to it."""
        with self._lock:
            if self.sweeps_planned is not None:
                return
            self.sweeps_planned = int(n)
        if self.manifest is not None:
            self.manifest.event("plan", sweepsPlanned=int(n))

    def wants_finite_check(self) -> bool:
        return self.watchdog.nan_check

    # -- per-sweep ---------------------------------------------------------
    def record_sweep(self, seconds: float, phases: dict,
                     loss: Optional[float] = None,
                     factors_finite: bool = True,
                     source=None) -> None:
        """Book one completed sweep: manifest record, progress state,
        cluster publish, then the watchdog verdict (raising
        :class:`ConvergenceError` AFTER the evidence is persisted, so
        an aborted run's manifest shows the sweep that killed it).

        ``source`` identifies the trainer reporting (eval sessions see
        many): a source change resets the watchdog's divergence window
        — one trainer's chunked run() calls share a window, two
        candidates' models never do."""
        mark = time.perf_counter()
        with self._lock:
            if source is not None and source != self._watch_source:
                self._watch_source = source
                self.watchdog.reset_losses()
            self._sweep += 1
            i = self._sweep
            if self._first_sweep_start is None:
                self._first_sweep_start = mark - seconds
            self._last_sweep_end = mark
            self._sweep_seconds_total += seconds
            for k, v in phases.items():
                self._phase_totals[k] = self._phase_totals.get(k, 0.0) + v
            if loss is not None and math.isfinite(loss):
                self._loss_history.append((i, float(loss)))
                del self._loss_history[:-256]
            events, self._pending_events = self._pending_events, []
            self._events_total += len(events)
            extras = {}
            hw = _device_high_water()
            if hw is not None:
                extras["deviceMemHighWater"] = hw
            delta = _compile_total() - self._compile_base
            self._compile_base += delta
            extras["compileDelta"] = delta
            if loss is not None:
                extras["loss"] = loss
            if events:
                extras["shardEvents"] = events
            self._last_sweep = {
                "i": i, "seconds": seconds,
                "phases": dict(phases), **extras,
            }
        if self.manifest is not None:
            self.manifest.sweep(i, round(seconds, 6), phases, **extras)
        if self._publisher is not None:
            self._publisher.publish()
        try:
            self.watchdog.check(i, seconds, loss, factors_finite)
        except ConvergenceError as e:
            self._abort(e)
            raise

    def note_shard_event(self, event: dict) -> None:
        """A degradation event from ``ShardHealth`` (parity serve,
        sticky kill): queued onto the next sweep record AND appended
        to the manifest immediately (a stalled run may never reach
        its next sweep record)."""
        with self._lock:
            self._pending_events.append(dict(event))
        if self.manifest is not None:
            self.manifest.event("shard_degraded", **event)

    def note_train_run(self, seconds: float) -> None:
        """The workflow layer reports the ``train.run`` span's wall
        time (read + prepare + staging + sweeps).  With the sweep
        marks this decomposes the whole span in the final record:
        setup (span start -> first sweep) + sweeps + tail (last sweep
        -> span end) — the cross-layer reconciliation
        ``tools/train_obs_smoke.py`` asserts to 2%."""
        with self._lock:
            self._train_run_seconds = float(seconds)
            self._train_run_end = time.perf_counter()

    # -- terminal ----------------------------------------------------------
    def _abort(self, e: ConvergenceError) -> None:
        TRAIN_ABORTS_TOTAL.labels(reason=e.reason).inc()
        if self.manifest is not None:
            self.manifest.event("watchdog_abort", reason=e.reason,
                                message=str(e))
        self.finalize("aborted", reason=e.reason, error=str(e))

    def finalize_error(self, exc: BaseException) -> None:
        """Terminal record for a run dying on an arbitrary exception.
        A :class:`ConvergenceError` was already finalized by
        :meth:`record_sweep`; anything else is ``failed``."""
        if isinstance(exc, ConvergenceError):
            self.finalize("aborted", reason=exc.reason, error=str(exc))
        else:
            self.finalize("failed", error=f"{type(exc).__name__}: {exc}")

    def finalize(self, status: str = "completed", **fields) -> None:
        with self._lock:
            if self._finalized:
                return
            self._finalized = True
            totals = dict(self._phase_totals)
            sweeps = self._sweep
            wall = time.perf_counter() - self._t0
            if self._train_run_seconds is not None:
                fields.setdefault(
                    "trainRunSeconds", round(self._train_run_seconds, 6)
                )
            if self._first_sweep_start is not None:
                fields.setdefault("setupSeconds", round(
                    self._first_sweep_start - self._t0, 6))
                end = self._train_run_end
                if end is not None and self._last_sweep_end is not None:
                    fields.setdefault("tailSeconds", round(
                        max(end - self._last_sweep_end, 0.0), 6))
            fields.setdefault(
                "sweepSecondsTotal", round(self._sweep_seconds_total, 6)
            )
        if self._aggregator is not None and self.manifest is not None:
            try:
                self.manifest.metrics(
                    self._aggregator.merged_state(),
                    workers=self._aggregator.workers_seen(),
                )
            except ValueError:
                pass  # schema drift across workers must not mask status
        if self.manifest is not None:
            self.manifest.finalize(
                status,
                sweeps=sweeps,
                wallSeconds=round(wall, 6),
                phaseTotals={k: round(v, 6) for k, v in totals.items()},
                **fields,
            )
        if active_session() is self:
            _set_active(None)
        if self._aggregator is not None:
            set_cluster_renderer(None)

    # -- live view ---------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            sweeps = self._sweep
            planned = self.sweeps_planned
            mean = (
                self._sweep_seconds_total / sweeps if sweeps else None
            )
            eta = (
                mean * (planned - sweeps)
                if mean is not None and planned and planned > sweeps
                else None
            )
            return {
                "instanceId": self.instance_id,
                "runKind": self.kind,
                "startedAt": self._start_wall,
                "elapsedSeconds": round(
                    time.perf_counter() - self._t0, 3),
                "sweep": sweeps,
                "sweepsPlanned": planned,
                "etaSeconds": round(eta, 3) if eta is not None else None,
                "lastSweep": self._last_sweep,
                "meanSweepSeconds": (
                    round(mean, 6) if mean is not None else None
                ),
                "phaseTotals": {
                    k: round(v, 6)
                    for k, v in sorted(self._phase_totals.items())
                },
                "lossHistory": self._loss_history[-32:],
                "shardEvents": self._events_total,
                "worker": self.worker,
                "workers": self.n_workers,
            }


# -- module-level hooks (what models/als.py calls) ---------------------------


def record_sweep(seconds: float, phases: dict,
                 loss: Optional[float] = None,
                 factors_finite: bool = True,
                 source=None) -> None:
    """The sweep loop's single reporting call.  Always-on metrics are
    booked session-or-not; with an active session the sweep also lands
    in the manifest / progress view / watchdog.  May raise
    :class:`ConvergenceError` (the typed abort) — the trainer lets it
    propagate."""
    TRAIN_SWEEPS_TOTAL.child().inc()
    TRAIN_LAST_SWEEP_SECONDS.child().set(seconds)
    TRAIN_SWEEP_SECONDS.child().observe(seconds)
    if loss is not None and math.isfinite(loss):
        TRAIN_LOSS.child().set(loss)
    session = active_session()
    if session is not None:
        session.record_sweep(
            seconds, phases, loss=loss, factors_finite=factors_finite,
            source=source,
        )


def note_shard_event(event: dict) -> None:
    session = active_session()
    if session is not None:
        session.note_shard_event(event)


def record_candidate(index: int, **fields) -> None:
    """One evaluation candidate scored (eval-run manifests)."""
    session = active_session()
    if session is not None and session.manifest is not None:
        session.manifest.candidate(index, **fields)


def train_payload() -> dict:
    """The ``GET /debug/train`` document: the in-process live session
    (if this process is training) plus manifest history from disk —
    including OTHER processes' live runs, whose manifests grow by one
    line per sweep, so a dashboard next to a training job is live
    without any extra port on the trainer."""
    session = active_session()
    runs = []
    for view in list_runs(limit=20):
        runs.append(summarize(view))
    return {
        "active": session.snapshot() if session is not None else None,
        "runs": runs,
    }


# -- lazy device/compiler reads (never raise into the sweep loop) -----------


def _compile_total() -> int:
    try:
        from .xray import total_backend_compiles

        return total_backend_compiles()
    except Exception:
        return 0


def _device_high_water() -> Optional[int]:
    try:
        from .xray import device_high_water, sample_devices_once

        sample_devices_once()
        return device_high_water()
    except Exception:
        return None
