"""Process-wide metrics registry: Counter / Gauge / Histogram with
Prometheus text exposition.

The reference's operators lived in the Spark UI and the evaluation
dashboard; here every server and workflow reports into ONE process-wide
:class:`MetricsRegistry` that any of the four HTTP servers exposes at
``GET /metrics`` (Prometheus text format 0.0.4).  Design constraints,
in order:

* **Hot-path cheap.** ``Counter.inc`` / ``Histogram.observe`` sit on
  the serving request path (p50 ~0.3 ms); both are a single sharded
  lock acquisition plus one or three scalar updates.  Shards are
  selected by thread identity, so concurrent request threads touch
  disjoint locks and the instruments never serialize the very
  concurrency they are measuring.
* **Lock-discipline clean.** Every class here passes piolint's PIO2xx
  engine: shared attributes are written only under their owning lock,
  snapshots are taken under the lock and rendered outside it, and
  user callbacks (gauge functions) are invoked OFF-lock so a callback
  touching another lock (a circuit breaker's, say) cannot deadlock
  the scrape.
* **Fixed buckets.** Histograms use log-spaced immutable bucket bounds
  chosen at construction: merging across shards, exposition, and
  p50/p95/p99 derivation are all exact bucket arithmetic — no
  reservoir sampling, no decay windows, no per-observation allocation.

Pure stdlib; importable from every layer without cycles (the same
contract resilience/policy.py keeps).
"""

from __future__ import annotations

import bisect
import math
import threading
import time
from typing import Callable, Iterable, Optional, Sequence

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "default_latency_buckets",
    "log_buckets",
    "merge_states",
    "render_state",
]

_N_SHARDS = 8  # power of two; thread-ident hash distributes across these


def log_buckets(lo: float, hi: float, per_decade: int = 4) -> tuple:
    """Log-spaced bucket upper bounds from ``lo`` to >= ``hi``.

    ``per_decade`` bounds per 10x; the +Inf bucket is implicit (every
    histogram always has it).  Bounds are rounded to 6 significant
    digits so the exposition's ``le`` labels stay stable across
    platforms' float printing.
    """
    if lo <= 0 or hi <= lo:
        raise ValueError("need 0 < lo < hi")
    step = 10.0 ** (1.0 / per_decade)
    out, v = [], lo
    while v < hi * (1.0 + 1e-9):
        out.append(float(f"{v:.6g}"))
        v *= step
    return tuple(out)


def default_latency_buckets() -> tuple:
    """10 us .. ~100 s, 8 per decade: fine enough that linear
    interpolation inside a bucket recovers p50 within a few percent of
    the exact sample percentile at serving-latency scales."""
    return log_buckets(1e-5, 100.0, per_decade=8)


class _Shard:
    """One lock-striped accumulator cell.  Accessed only through a
    local variable (``shard = self._shards[i]``), which also keeps the
    lock discipline trivially checkable."""

    __slots__ = ("lock", "value", "counts", "total", "n")

    def __init__(self, n_buckets: int = 0):
        self.lock = threading.Lock()
        self.value = 0.0
        # histogram-only state (unused by counters)
        self.counts = [0] * (n_buckets + 1)  # +1: the +Inf bucket
        self.total = 0.0
        self.n = 0


def _shard_index() -> int:
    return threading.get_ident() & (_N_SHARDS - 1)


class Counter:
    """Monotonically increasing value (Prometheus counter)."""

    kind = "counter"

    def __init__(self):
        self._shards = tuple(_Shard() for _ in range(_N_SHARDS))

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up; use a Gauge")
        shard = self._shards[_shard_index()]
        with shard.lock:
            shard.value += n

    def value(self) -> float:
        total = 0.0
        for shard in self._shards:
            with shard.lock:
                total += shard.value
        return total

    def samples(self, name: str, labels: tuple) -> list:
        return [(name, labels, self.value())]

    def state(self) -> dict:
        """Serializable snapshot for the cluster aggregator."""
        return {"value": self.value()}


class Gauge:
    """Set-anywhere value, or a callback read at scrape time.

    ``set_function`` wins over ``set`` while installed; the callback is
    invoked OUTSIDE the gauge's lock (it may read other locks — e.g. a
    circuit breaker snapshot).
    """

    kind = "gauge"

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self._value -= n

    def set_function(self, fn: Optional[Callable[[], float]]) -> None:
        with self._lock:
            self._fn = fn

    def value(self) -> float:
        with self._lock:
            fn = self._fn
            v = self._value
        if fn is not None:
            try:
                return float(fn())
            except Exception:
                return float("nan")  # a broken callback must not 500 /metrics
        return v

    def samples(self, name: str, labels: tuple) -> list:
        return [(name, labels, self.value())]

    def state(self) -> dict:
        return {"value": self.value()}


class Histogram:
    """Fixed log-spaced buckets with cumulative exposition and
    percentile derivation.

    ``observe`` is shard-local: bisect into the immutable bounds, one
    lock, three scalar updates.  ``snapshot`` merges shards under each
    shard's lock; percentiles interpolate linearly inside the target
    bucket (the same estimate ``histogram_quantile`` makes), so the
    numbers on ``/status`` and in Grafana agree by construction.

    *Exemplars* (pio-xray): ``observe(v, exemplar="t-...")`` remembers
    the most recent exemplar string (a trace id) per bucket, so a slow
    bucket on ``/metrics`` points at a concrete request whose span tree
    the flight recorder / journal holds.  Stored outside the shards
    (one small dict under its own lock — only callers that pass an
    exemplar pay for it) and rendered as ``# EXEMPLAR`` comment lines,
    which every 0.0.4 text parser ignores by definition.
    """

    kind = "histogram"

    def __init__(self, buckets: Optional[Sequence[float]] = None):
        bounds = tuple(buckets) if buckets is not None \
            else default_latency_buckets()
        if list(bounds) != sorted(bounds) or len(set(bounds)) != len(bounds):
            raise ValueError("bucket bounds must be strictly increasing")
        if not bounds:
            raise ValueError("need at least one bucket bound")
        self.bounds = bounds
        self._shards = tuple(
            _Shard(n_buckets=len(bounds)) for _ in range(_N_SHARDS)
        )
        self._ex_lock = threading.Lock()
        # bucket index -> (exemplar, observed value, wall timestamp)
        self._exemplars: dict[int, tuple] = {}

    def observe(self, v: float, exemplar: Optional[str] = None) -> None:
        i = bisect.bisect_left(self.bounds, v)
        shard = self._shards[_shard_index()]
        with shard.lock:
            shard.counts[i] += 1
            shard.total += v
            shard.n += 1
        if exemplar is not None:
            # last-exemplar-wins per bucket (the standard exemplar
            # semantic); wall clock is a timestamp, not a duration
            with self._ex_lock:
                self._exemplars[i] = (exemplar, v, time.time())

    def exemplar_items(self) -> list:
        """``(le_label, exemplar, value, timestamp)`` per bucket that
        has one, in bucket order."""
        with self._ex_lock:
            snap = dict(self._exemplars)
        out = []
        for i in sorted(snap):
            le = (
                _fmt_float(self.bounds[i]) if i < len(self.bounds)
                else "+Inf"
            )
            ex, v, ts = snap[i]
            out.append((le, ex, v, ts))
        return out

    def snapshot(self) -> dict:
        """Merged view: per-bucket counts (non-cumulative), sum, count."""
        counts = [0] * (len(self.bounds) + 1)
        total, n = 0.0, 0
        for shard in self._shards:
            with shard.lock:
                sc = list(shard.counts)
                total += shard.total
                n += shard.n
            for i, c in enumerate(sc):
                counts[i] += c
        return {"counts": counts, "sum": total, "count": n}

    # -- derived stats -----------------------------------------------------
    def percentile(self, q: float, snap: Optional[dict] = None) -> float:
        """Estimate the q-th percentile (q in [0, 100]) from bucket
        counts; NaN when empty.  Linear interpolation inside the target
        bucket; the +Inf bucket answers its lower bound (the last
        finite bound) — the honest cap for an unbounded tail."""
        snap = snap or self.snapshot()
        n = snap["count"]
        if n == 0:
            return float("nan")
        rank = (q / 100.0) * n
        cum = 0
        for i, c in enumerate(snap["counts"]):
            if c == 0:
                continue
            if cum + c >= rank:
                if i >= len(self.bounds):
                    return self.bounds[-1]
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i]
                frac = (rank - cum) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            cum += c
        return self.bounds[-1]

    def percentiles(self, qs: Iterable[float]) -> dict:
        snap = self.snapshot()
        return {q: self.percentile(q, snap) for q in qs}

    def mean(self, snap: Optional[dict] = None) -> float:
        snap = snap or self.snapshot()
        return snap["sum"] / snap["count"] if snap["count"] else 0.0

    def samples(self, name: str, labels: tuple) -> list:
        snap = self.snapshot()
        out = []
        cum = 0
        for bound, c in zip(self.bounds, snap["counts"]):
            cum += c
            out.append((name + "_bucket",
                        labels + (("le", _fmt_float(bound)),), cum))
        out.append((name + "_bucket", labels + (("le", "+Inf"),),
                    snap["count"]))
        out.append((name + "_sum", labels, snap["sum"]))
        out.append((name + "_count", labels, snap["count"]))
        return out

    def state(self) -> dict:
        snap = self.snapshot()
        return {"hist": {
            "bounds": list(self.bounds),
            "counts": list(snap["counts"]),
            "sum": snap["sum"],
            "count": snap["count"],
            "exemplars": [list(t) for t in self.exemplar_items()],
        }}


def _fmt_float(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _fmt_value(v: float) -> str:
    if isinstance(v, int):
        return str(v)
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')


_NAME_OK = set("abcdefghijklmnopqrstuvwxyz"
               "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_:")


def _check_name(name: str) -> str:
    if not name or name[0].isdigit() or any(c not in _NAME_OK for c in name):
        raise ValueError(f"invalid metric name: {name!r}")
    return name


class _Family:
    """One named metric family: HELP/TYPE plus labeled children."""

    def __init__(self, name: str, help_text: str, kind: str,
                 label_names: tuple, child_ctor: Callable):
        self.name = _check_name(name)
        self.help_text = help_text
        self.kind = kind
        self.label_names = label_names
        self._ctor = child_ctor
        self._lock = threading.Lock()
        self._children: dict[tuple, object] = {}

    def labels(self, **kv):
        """The child instrument for these label values (created on
        first use).  Label names must match the family declaration."""
        if tuple(sorted(kv)) != tuple(sorted(self.label_names)):
            raise ValueError(
                f"{self.name}: labels {sorted(kv)} != declared "
                f"{sorted(self.label_names)}"
            )
        key = tuple((k, str(kv[k])) for k in self.label_names)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._ctor()
                self._children[key] = child
        return child

    def child(self):
        """The unlabeled child (only valid for label-less families)."""
        if self.label_names:
            raise ValueError(f"{self.name} is labeled; use .labels()")
        return self.labels()

    def children(self) -> list:
        """Sorted ``(label_items, child)`` snapshot."""
        with self._lock:
            return sorted(self._children.items())

    def collect(self) -> list:
        out = []
        for key, child in self.children():
            out += child.samples(self.name, key)
        return out


class MetricsRegistry:
    """Name -> family table with idempotent registration.

    Re-registering an existing name returns the SAME family (the
    process may build several servers that all want
    ``pio_query_latency_seconds``); a kind or label mismatch raises —
    that is a programming error, not a collision to paper over.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}

    def _register(self, name: str, help_text: str, kind: str,
                  label_names: Sequence[str], ctor: Callable) -> _Family:
        label_names = tuple(label_names)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = _Family(name, help_text, kind, label_names, ctor)
                self._families[name] = fam
        if fam.kind != kind or fam.label_names != label_names:
            raise ValueError(
                f"metric {name!r} already registered as {fam.kind} with "
                f"labels {fam.label_names}; got {kind}/{label_names}"
            )
        return fam

    def counter(self, name: str, help_text: str = "",
                labels: Sequence[str] = ()) -> _Family:
        return self._register(name, help_text, "counter", labels, Counter)

    def gauge(self, name: str, help_text: str = "",
              labels: Sequence[str] = ()) -> _Family:
        return self._register(name, help_text, "gauge", labels, Gauge)

    def histogram(self, name: str, help_text: str = "",
                  labels: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> _Family:
        return self._register(
            name, help_text, "histogram", labels,
            lambda: Histogram(buckets=buckets),
        )

    def families(self) -> list:
        with self._lock:
            return sorted(self._families.values(), key=lambda f: f.name)

    def collect(self) -> list:
        """Flat ``(name, label_items, value)`` sample list (all
        families) — the dashboard's live-metrics page renders this."""
        out = []
        for fam in self.families():
            out += fam.collect()
        return out

    def dump_state(self) -> dict:
        """JSON-serializable snapshot of the WHOLE registry — the
        per-worker delta the pio-tower cluster aggregator ships through
        the coordination dir each sweep (values are cumulative, so a
        re-read of the newest file always supersedes older ones and a
        worker that dies mid-run leaves its last snapshot standing)."""
        fams = []
        for fam in self.families():
            fams.append({
                "name": fam.name,
                "help": fam.help_text,
                "kind": fam.kind,
                "labelNames": list(fam.label_names),
                "children": [
                    {"labels": [list(kv) for kv in key], **child.state()}
                    for key, child in fam.children()
                ],
            })
        return {"families": fams}

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4.

        Rendering goes through :func:`render_state` so a merged
        multi-worker state (pio-tower) and a live registry produce
        byte-identical text for identical contents — the golden-merge
        test depends on there being exactly ONE renderer."""
        return render_state(self.dump_state())


# -- state rendering + cluster merge (pio-tower) ----------------------------


def render_state(state: dict) -> str:
    """Prometheus text format 0.0.4 for a :meth:`MetricsRegistry.
    dump_state` snapshot (or a :func:`merge_states` result)."""
    lines = []
    for fam in sorted(state["families"], key=lambda f: f["name"]):
        if fam["help"]:
            lines.append(f"# HELP {fam['name']} "
                         + fam["help"].replace("\n", " "))
        lines.append(f"# TYPE {fam['name']} {fam['kind']}")
        children = sorted(
            fam["children"], key=lambda c: [tuple(kv) for kv in c["labels"]]
        )
        for child in children:
            labels = tuple(tuple(kv) for kv in child["labels"])
            hist = child.get("hist")
            if hist is None:
                lines.append(_sample_line(
                    fam["name"], labels, child["value"]
                ))
                continue
            cum = 0
            for bound, c in zip(hist["bounds"], hist["counts"]):
                cum += c
                lines.append(_sample_line(
                    fam["name"] + "_bucket",
                    labels + (("le", _fmt_float(bound)),), cum,
                ))
            lines.append(_sample_line(
                fam["name"] + "_bucket", labels + (("le", "+Inf"),),
                hist["count"],
            ))
            lines.append(_sample_line(
                fam["name"] + "_sum", labels, hist["sum"]
            ))
            lines.append(_sample_line(
                fam["name"] + "_count", labels, hist["count"]
            ))
        if fam["kind"] == "histogram":
            for child in children:
                hist = child.get("hist") or {}
                base = ",".join(
                    f'{k}="{_escape_label(v)}"'
                    for k, v in (tuple(kv) for kv in child["labels"])
                )
                # ``# EXEMPLAR`` comment lines: legal-by-construction
                # in text format 0.0.4 (parsers skip comments), yet a
                # ``grep t-xxxx`` on a scrape finds the trace id a slow
                # bucket points at
                for le, ex, v, ts in hist.get("exemplars", ()):
                    lbl = (base + "," if base else "") + f'le="{le}"'
                    lines.append(
                        f"# EXEMPLAR {fam['name']}_bucket{{{lbl}}} "
                        f'trace_id="{_escape_label(str(ex))}" '
                        f"value={_fmt_value(v)} ts={_fmt_value(ts)}"
                    )
    return "\n".join(lines) + "\n"


def _sample_line(name: str, label_items: tuple, value) -> str:
    if label_items:
        lbl = ",".join(
            f'{k}="{_escape_label(v)}"' for k, v in label_items
        )
        return f"{name}{{{lbl}}} {_fmt_value(value)}"
    return f"{name} {_fmt_value(value)}"


def merge_states(tagged: Sequence[tuple],
                 gauge_label: str = "worker") -> dict:
    """Merge per-worker registry snapshots into one cluster state.

    ``tagged`` is ``[(worker_id, state), ...]``.  Merge semantics (the
    table in docs/ARCHITECTURE.md "Tower"):

    * **counters** sum exactly across workers (same labels = one
      child);
    * **histograms** add bucket-wise: identical bucket ladders are
      required (the eager family catalog guarantees it), counts merge
      elementwise, sum/count add — so percentile re-derivation on the
      merged exposition is exact bucket arithmetic over the union of
      observations, byte-identical to a single process that saw them
      all; per-bucket exemplars keep the newest timestamp;
    * **gauges** are NOT summable (a per-worker queue depth summed is
      a lie); every gauge child instead gains a ``gauge_label`` label
      (``worker`` for the pio-tower cluster merge, ``replica`` for the
      pio-lens fleet merge) so the merged view shows each process's
      value side by side.  A gauge family that ALREADY carries that
      label name (the router's own ``pio_replica_up{replica=}``) keeps
      its labels untouched — the attribution it wants is already
      there, and a duplicate label name would be grammar-invalid.

    A kind/label/bucket mismatch raises ``ValueError`` — that is a
    schema drift bug, not a collision to paper over.
    """
    fams: dict[str, dict] = {}
    for worker, state in tagged:
        for fam in state["families"]:
            name = fam["name"]
            tag_gauges = (
                fam["kind"] == "gauge"
                and gauge_label not in fam["labelNames"]
            )
            mine = fams.get(name)
            if mine is None:
                mine = {
                    "name": name,
                    "help": fam["help"],
                    "kind": fam["kind"],
                    "labelNames": list(fam["labelNames"]),
                    "children": {},
                }
                if tag_gauges:
                    mine["labelNames"] = (
                        mine["labelNames"] + [gauge_label]
                    )
                fams[name] = mine
            elif mine["kind"] != fam["kind"]:
                raise ValueError(
                    f"metric {name!r}: kind mismatch across workers "
                    f"({mine['kind']} vs {fam['kind']})"
                )
            for child in fam["children"]:
                labels = tuple(tuple(kv) for kv in child["labels"])
                if fam["kind"] == "gauge":
                    if tag_gauges:
                        labels = labels + ((gauge_label, str(worker)),)
                    mine["children"][labels] = {
                        "labels": [list(kv) for kv in labels],
                        "value": child["value"],
                    }
                    continue
                have = mine["children"].get(labels)
                if have is None:
                    merged = {
                        "labels": [list(kv) for kv in labels],
                    }
                    if "hist" in child:
                        h = child["hist"]
                        merged["hist"] = {
                            "bounds": list(h["bounds"]),
                            "counts": list(h["counts"]),
                            "sum": h["sum"],
                            "count": h["count"],
                            "exemplars": [list(t) for t in
                                          h.get("exemplars", ())],
                        }
                    else:
                        merged["value"] = child["value"]
                    mine["children"][labels] = merged
                    continue
                if "hist" in child:
                    h, hv = child["hist"], have["hist"]
                    if list(h["bounds"]) != list(hv["bounds"]):
                        raise ValueError(
                            f"metric {name!r}: bucket ladder mismatch "
                            "across workers"
                        )
                    hv["counts"] = [
                        a + b for a, b in zip(hv["counts"], h["counts"])
                    ]
                    hv["sum"] += h["sum"]
                    hv["count"] += h["count"]
                    by_le = {e[0]: e for e in hv.get("exemplars", ())}
                    for e in h.get("exemplars", ()):
                        cur = by_le.get(e[0])
                        if cur is None or e[3] >= cur[3]:
                            by_le[e[0]] = list(e)
                    hv["exemplars"] = [
                        by_le[le] for le in sorted(
                            by_le,
                            key=lambda s: (
                                float("inf") if s == "+Inf" else float(s)
                            ),
                        )
                    ]
                else:
                    have["value"] += child["value"]
    return {"families": [
        {**f, "children": list(f["children"].values())}
        for f in sorted(fams.values(), key=lambda f: f["name"])
    ]}
