"""`python -m predictionio_tpu` -> the pio-tpu console."""

import sys

from .cli.main import main

sys.exit(main())
