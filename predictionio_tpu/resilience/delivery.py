"""Bounded background delivery queue for fire-and-forget HTTP sends.

Replaces the thread-per-request daemon threads serving used for
feedback events and remote error logs: under heavy traffic with a slow
or dead collector those threads accumulate without bound (each holding
a socket for its full timeout).  Here ONE drain thread works a bounded
deque:

* ``submit`` is O(1) and never blocks the hot path; when the queue is
  full the OLDEST entry is dropped (and counted) — fresh telemetry
  beats stale telemetry, and memory stays bounded.
* the drain thread retries each entry with the policy's backoff and
  routes every outcome through a :class:`CircuitBreaker`, so a dead
  endpoint costs one probe per reset interval instead of a connect
  timeout per request.
* an entry is only discarded after delivery or ``max_attempts``
  failures while the breaker was willing — with the breaker OPEN the
  entry waits (no attempts burned), which is what lets events queued
  while the event server was down deliver once it returns.
"""

from __future__ import annotations

import collections
import json
import logging
import threading
import urllib.request
from typing import Optional

from . import faults
from ..obs import BREAKER_STATE_VALUES, BREAKER_STATE, DELIVERY_DEPTH, \
    DELIVERY_TOTAL
from .policy import CircuitBreaker, RetryPolicy

logger = logging.getLogger(__name__)

__all__ = ["DeliveryQueue"]


class _Entry:
    __slots__ = ("url", "data", "attempts", "headers")

    def __init__(self, url: str, data: bytes,
                 headers: Optional[dict] = None):
        self.url = url
        self.data = data
        self.attempts = 0
        self.headers = headers or {}


class DeliveryQueue:
    def __init__(
        self,
        name: str,
        capacity: int = 1024,
        retry: Optional[RetryPolicy] = None,
        breaker: Optional[CircuitBreaker] = None,
        timeout_s: float = 2.0,
        fault_point: Optional[str] = None,
    ):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.name = name
        self.capacity = capacity
        self.retry = retry or RetryPolicy(max_attempts=4, base_s=0.1,
                                          cap_s=5.0)
        self.breaker = breaker or CircuitBreaker(failure_threshold=5,
                                                 reset_timeout_s=10.0)
        self.timeout_s = timeout_s
        self.fault_point = fault_point
        self._dq: collections.deque[_Entry] = collections.deque()
        self._cond = threading.Condition()
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self._wake = threading.Event()  # cut breaker/backoff sleeps short
        # counters (read under _cond for a consistent stats() view)
        self.submitted = 0
        self.delivered = 0
        self.dropped = 0
        self.retries = 0
        self.send_failures = 0
        # pio-obs wiring: this queue's breaker state and depth as
        # callback gauges, outcomes as counters.  Gauge children are
        # keyed by queue name — the freshest same-named queue owns the
        # child (the steady state: one live queue per name per process).
        BREAKER_STATE.labels(queue=name).set_function(
            lambda b=self.breaker: BREAKER_STATE_VALUES.get(b.state, -1.0)
        )
        DELIVERY_DEPTH.labels(queue=name).set_function(lambda: self.depth)
        self._m_outcome = {
            k: DELIVERY_TOTAL.labels(queue=name, outcome=k)
            for k in ("submitted", "delivered", "dropped", "retried")
        }

    # -- producer side -----------------------------------------------------
    def submit(self, url: str, payload,
               headers: Optional[dict] = None) -> bool:
        """Enqueue one delivery; returns False when it displaced the
        oldest queued entry (queue at capacity).  ``headers`` are extra
        HTTP headers sent with the POST — trace propagation
        (``X-PIO-Trace``) rides here."""
        data = (payload if isinstance(payload, (bytes, bytearray))
                else json.dumps(payload).encode())
        kept = True
        with self._cond:
            if self._closed:
                self.dropped += 1
                self._m_outcome["dropped"].inc()
                return False
            self.submitted += 1
            self._m_outcome["submitted"].inc()
            if len(self._dq) >= self.capacity:
                self._dq.popleft()
                self.dropped += 1
                self._m_outcome["dropped"].inc()
                kept = False
            self._dq.append(_Entry(url, data, headers))
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._drain, daemon=True,
                    name=f"delivery-{self.name}",
                )
                self._thread.start()
            self._cond.notify()
        return kept

    # -- drain thread ------------------------------------------------------
    def _drain(self) -> None:
        while True:
            with self._cond:
                while not self._dq and not self._closed:
                    self._cond.wait()
                if self._closed and not self._dq:
                    return
                entry = self._dq[0]  # keep queued until resolved
            if not self.breaker.allow():
                # open breaker: hold position, nap, re-check (a probe
                # slot frees when the reset timeout passes)
                self._wake.wait(min(0.05, self.breaker.reset_timeout_s))
                self._wake.clear()
                if self._closed_now():
                    return
                continue
            try:
                self._send(entry)
            except Exception as e:
                self.breaker.record_failure()
                entry.attempts += 1
                with self._cond:
                    self.send_failures += 1
                    if entry.attempts >= self.retry.max_attempts:
                        # undeliverable: give its slot to fresher data
                        if self._dq and self._dq[0] is entry:
                            self._dq.popleft()
                        self.dropped += 1
                        self._m_outcome["dropped"].inc()
                        logger.warning(
                            "%s delivery dropped after %d attempts: %s",
                            self.name, entry.attempts, e,
                        )
                        continue
                    self.retries += 1
                    self._m_outcome["retried"].inc()
                self._wake.wait(self.retry.backoff(entry.attempts))
                self._wake.clear()
                if self._stopping():
                    return
            else:
                self.breaker.record_success()
                with self._cond:
                    if self._dq and self._dq[0] is entry:
                        self._dq.popleft()
                    self.delivered += 1
                    self._m_outcome["delivered"].inc()
                    self._cond.notify_all()  # flush() waiters

    def _closed_now(self) -> bool:
        with self._cond:
            return self._closed

    def _stopping(self) -> bool:
        # closed AND drained, read atomically — the drain thread checks
        # this off-lock after a backoff nap
        with self._cond:
            return self._closed and not self._dq

    def _send(self, entry: _Entry) -> None:
        if self.fault_point is not None:
            faults.check(self.fault_point)
        req = urllib.request.Request(
            entry.url, data=entry.data,
            headers={"Content-Type": "application/json", **entry.headers},
            method="POST",
        )
        # context manager: the response socket must close on every path
        with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
            r.read()

    # -- lifecycle / observability ----------------------------------------
    def flush(self, timeout_s: float = 10.0) -> bool:
        """Block until the queue drains (True) or the timeout passes
        (False).  Test/shutdown helper — production never calls it on
        the hot path."""
        import time

        deadline = time.monotonic() + timeout_s
        with self._cond:
            while self._dq:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cond.wait(min(left, 0.05))
            return True

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._wake.set()

    @property
    def depth(self) -> int:
        with self._cond:
            return len(self._dq)

    def stats(self) -> dict:
        with self._cond:
            return {
                "depth": len(self._dq),
                "capacity": self.capacity,
                "submitted": self.submitted,
                "delivered": self.delivered,
                "dropped": self.dropped,
                "retries": self.retries,
                "sendFailures": self.send_failures,
                "breaker": self.breaker.snapshot(),
            }
