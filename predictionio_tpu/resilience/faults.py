"""Deterministic fault injection: named points in the real code paths.

Production code calls :func:`check` at each named boundary; with no
plan armed that is one module-global load and a ``None`` compare — the
happy path costs nothing.  Tests (or an operator reproducing an
incident) arm a plan programmatically or through the ``PIO_FAULT_PLAN``
environment variable and the *real* serving/ingestion/restore code
executes its degradation paths.

Injection points instrumented in this codebase::

    storage.write      event-server storage inserts
    storage.read       event-server storage scans
    device.dispatch    serving predict just before the device call
    http.feedback      feedback-event delivery (delivery queue send)
    http.remote_log    remote error-log delivery (delivery queue send)
    reload.load_model  engine (re)load of trained components
    dist.shard_delay   a factor/item shard is SLOW this half/hop
                       (straggler; consulted via :func:`fired_shard`)
    dist.shard_drop    a shard's data is unavailable for ONE half/hop
                       (transient loss; consulted via :func:`fired_shard`)
    dist.worker_kill   a worker dies; its shard is gone for the REST of
                       the run (sticky — the coded orchestration in
                       ``parallel/coded.py`` remembers the kill)
    dist.exchange_torn the sharded-COO file exchange tears mid-publish
                       (`parallel/ingest.exchange_ratings_by_owner`)
    train.nan          the ALS sweep loop poisons the factor tables
                       with NaN after the targeted sweep (consulted via
                       :func:`fired` — the pio-tower convergence
                       watchdog must turn it into a typed abort)
    tenant.dispatch    the per-tenant serving path just before device
                       work (pio-hive; consulted via
                       :func:`check_tenant` — a ``tenant=app/variant``
                       option scopes the rule to ONE tenant, the
                       isolation-chaos selector)
    store.shard_down   one event-store shard is unreachable (pio-levee;
                       consulted via :func:`check_shard` with a
                       ``shard=I`` selector — writes to that shard get
                       a structured 503, scans stall only that cursor
                       component; other shards don't even count calls)
    wal.torn           the ingest WAL append tears mid-record (the
                       group-commit leader dies between write and
                       fsync; consulted via :func:`check_shard` —
                       ``shard=I`` scopes the tear to one shard's log;
                       replay on restart must drop exactly the torn
                       tail)

Plan grammar (``;``-separated rules, ``,``-separated options)::

    PIO_FAULT_PLAN="storage.write:nth=1,times=2,exc=operational"
    PIO_FAULT_PLAN="seed=7;http.feedback:prob=0.5;device.dispatch:delay=0.05"
    PIO_FAULT_PLAN="dist.shard_delay:shard=1,delay=0.2,times=1"

Options per rule:

* ``nth=N``   — first firing call (1-based, default 1)
* ``times=T`` — stop after T firings (default: unlimited)
* ``prob=P``  — fire each eligible call with probability P from a
  seeded per-point RNG (same plan + seed => same firing sequence)
* ``delay=S`` — sleep S seconds when firing (without ``exc``: a pure
  slowdown, the way to exercise deadlines)
* ``exc=NAME`` — exception to raise: ``fault`` (default,
  :class:`InjectedFault`), ``operational`` (sqlite3.OperationalError),
  ``oserror``, ``timeout``, ``urlerror``
* ``shard=I`` — the target shard of a ``dist.*`` rule (0-based mesh
  shard index, default 0); returned by :func:`fired_shard` so the
  distributed orchestration knows WHICH shard to degrade
* ``tenant=APP/VARIANT`` — scope the rule to one tenant's calls at a
  :func:`check_tenant` boundary (other tenants don't even count calls)

Two consultation styles:

* :func:`check` — raise-or-sleep, for I/O boundaries whose degradation
  is an exception path (the original six points; ``dist.exchange_torn``).
* :func:`fired_shard` — ask-and-degrade, for the distributed
  orchestration: counts the call, applies the rule's delay, and returns
  the target shard id instead of raising — the caller's job is to serve
  that shard from parity, not to unwind.
"""

from __future__ import annotations

import os
import random
import sqlite3
import threading
import time
import urllib.error
from typing import Optional

__all__ = ["InjectedFault", "FaultRule", "FaultPlan", "POINTS",
           "arm", "disarm", "armed", "check", "check_shard",
           "check_tenant", "fired", "fired_shard"]

POINTS = (
    "storage.write",
    "storage.read",
    "device.dispatch",
    "http.feedback",
    "http.remote_log",
    "reload.load_model",
    "dist.shard_delay",
    "dist.shard_drop",
    "dist.worker_kill",
    "dist.exchange_torn",
    "train.nan",
    "tenant.dispatch",
    "store.shard_down",
    "wal.torn",
)


class InjectedFault(RuntimeError):
    """The default exception a firing injection point raises."""


def _make_exc(name: str, msg: str) -> BaseException:
    if name == "fault":
        return InjectedFault(msg)
    if name == "operational":
        return sqlite3.OperationalError(msg)
    if name == "oserror":
        return OSError(msg)
    if name == "timeout":
        return TimeoutError(msg)
    if name == "urlerror":
        return urllib.error.URLError(msg)
    raise ValueError(f"unknown fault exception kind {name!r}")


class FaultRule:
    def __init__(self, point: str, nth: int = 1,
                 times: Optional[int] = None, prob: Optional[float] = None,
                 delay: Optional[float] = None, exc: Optional[str] = None,
                 seed: Optional[int] = None, shard: Optional[int] = None,
                 tenant: Optional[str] = None):
        if point not in POINTS:
            raise ValueError(
                f"unknown injection point {point!r}; known: {POINTS}"
            )
        if nth < 1:
            # nth is 1-based ("first firing call"); 0 would silently mean
            # the same as 1, and a negative value is always a typo
            raise ValueError(f"nth must be >= 1 (1-based), got {nth}")
        if times is not None and times < 1:
            raise ValueError(f"times must be >= 1, got {times}")
        if shard is not None and shard < 0:
            raise ValueError(f"shard must be >= 0, got {shard}")
        if exc is not None:
            _make_exc(exc, "probe")  # validate the name at parse time
        self.point = point
        self.nth = nth
        self.shard = shard
        # pio-hive: a `tenant=app/variant` rule fires only for that
        # tenant's calls (the per-tenant isolation chaos selector);
        # None matches every tenant
        self.tenant = tenant
        self.times = times
        self.prob = prob
        self.delay = delay
        # a pure-delay rule raises nothing; otherwise default InjectedFault
        self.exc = exc if exc is not None else (
            None if delay is not None else "fault"
        )
        # per-point RNG stream: a rule's firing sequence depends only on
        # its own call order, not on when OTHER points were checked
        self._rng = random.Random(f"{seed}:{point}")
        self.calls = 0
        self.fires = 0

    def hit(self) -> tuple[bool, Optional[BaseException]]:
        """Count one call; decide whether this call fires and what (if
        anything) to raise.  Caller holds the plan lock."""
        self.calls += 1
        if self.calls < self.nth:
            return False, None
        if self.times is not None and self.fires >= self.times:
            return False, None
        if self.prob is not None and self._rng.random() >= self.prob:
            return False, None
        self.fires += 1
        exc = None if self.exc is None else _make_exc(
            self.exc,
            f"injected fault at {self.point} (call {self.calls})",
        )
        return True, exc


class FaultPlan:
    """A set of rules, at most one per point, plus the firing log."""

    def __init__(self, rules: list[FaultRule]):
        seen: set[str] = set()
        for r in rules:
            if r.point in seen:
                # silently keeping the LAST rule (the old dict-build
                # behavior) made a mistyped two-rule plan test only half
                # of what the operator thought it armed
                raise ValueError(
                    f"duplicate rule for injection point {r.point!r}; "
                    "a plan holds at most one rule per point"
                )
            seen.add(r.point)
        self._rules = {r.point: r for r in rules}
        self._lock = threading.Lock()
        # (point, call_index) per firing — the observable sequence a
        # determinism test compares across identically-seeded runs
        self.log: list[tuple[str, int]] = []

    @classmethod
    def parse(cls, spec: str, seed: Optional[int] = None) -> "FaultPlan":
        rules = []
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            if ":" not in part:
                if part in POINTS:
                    # a bare point name is a rule with defaults (fires
                    # every call with the point's default exception)
                    rules.append(FaultRule(part, seed=seed))
                    continue
                k, _, v = part.partition("=")
                if k.strip() != "seed":
                    raise ValueError(f"bad fault rule {part!r}")
                seed = int(v)
                continue
            point, _, opts = part.partition(":")
            kw: dict = {}
            for opt in opts.split(","):
                if not opt.strip():
                    continue
                k, _, v = opt.partition("=")
                k = k.strip()
                if k in ("nth", "times", "shard"):
                    kw[k] = int(v)
                elif k in ("prob", "delay"):
                    kw[k] = float(v)
                elif k == "exc":
                    kw[k] = v.strip()
                elif k == "seed":
                    kw[k] = int(v)
                elif k == "tenant":
                    kw[k] = v.strip()
                else:
                    raise ValueError(f"unknown fault option {k!r}")
            kw.setdefault("seed", seed)
            rules.append(FaultRule(point.strip(), **kw))
        return cls(rules)

    def hit(self, point: str, tenant: Optional[str] = None,
            shard: Optional[int] = None) -> None:
        rule = self._rules.get(point)
        if rule is None:
            return
        if rule.tenant is not None and tenant != rule.tenant:
            # a tenant-scoped rule is invisible to other tenants' calls
            # (not even counted: nth/times describe the TARGET tenant's
            # call sequence, which is what makes isolation plans
            # deterministic under interleaved multi-tenant traffic)
            return
        if rule.shard is not None and shard is not None \
                and shard != rule.shard:
            # same scoping for shard-addressed boundaries (pio-levee
            # ``store.shard_down`` / ``wal.torn``): a ``shard=I`` rule
            # only counts the TARGET shard's calls, so nth/times stay
            # deterministic while other shards' traffic interleaves
            return
        with self._lock:
            fired, exc = rule.hit()
            if fired:
                self.log.append((point, rule.calls))
        if not fired:
            return
        if rule.delay:
            time.sleep(rule.delay)  # outside the lock: other points flow
        if exc is not None:
            raise exc

    def hit_shard(self, point: str,
                  max_wait: Optional[float] = None
                  ) -> Optional[tuple[int, float]]:
        """Ask-and-degrade consultation: count one call; when the rule
        fires, return ``(target shard, injected lag)`` instead of
        raising.  The distributed caller degrades that shard (parity
        serve / frozen writes) rather than unwinding — a straggler is
        not an exception, it is a slower answer.

        ``max_wait`` caps how long this host actually SLEEPS waiting on
        the simulated straggler (the caller's hop budget); the returned
        lag is the rule's FULL delay, so the caller can tell "answered
        late but in budget" from "missed the budget — stop waiting and
        serve parity".  ``None`` waits the delay out in full."""
        rule = self._rules.get(point)
        if rule is None:
            return None
        with self._lock:
            fired, _ = rule.hit()
            if fired:
                self.log.append((point, rule.calls))
        if not fired:
            return None
        lag = rule.delay or 0.0
        wait = lag if max_wait is None else min(lag, max(max_wait, 0.0))
        if wait:
            time.sleep(wait)  # outside the lock: other points flow
        return (rule.shard if rule.shard is not None else 0), lag

    def counters(self) -> dict:
        with self._lock:
            return {
                p: {"calls": r.calls, "fires": r.fires}
                for p, r in self._rules.items()
            }


_plan: Optional[FaultPlan] = None


def arm(plan_or_spec, seed: Optional[int] = None) -> FaultPlan:
    """Activate a plan (replacing any armed one) and return it."""
    global _plan
    plan = (plan_or_spec if isinstance(plan_or_spec, FaultPlan)
            else FaultPlan.parse(plan_or_spec, seed=seed))
    _plan = plan
    return plan


def disarm() -> None:
    global _plan
    _plan = None


def armed() -> Optional[FaultPlan]:
    return _plan


def check(point: str) -> None:
    """The instrumented boundary.  No plan armed => one global load."""
    plan = _plan
    if plan is None:
        return
    plan.hit(point)


def fired_shard(point: str,
                max_wait: Optional[float] = None
                ) -> Optional[tuple[int, float]]:
    """Distributed instrumented boundary (``dist.shard_delay`` /
    ``dist.shard_drop`` / ``dist.worker_kill``): returns ``(shard id,
    injected lag)`` when the armed rule fires, else None.  The host
    sleeps at most ``max_wait`` of the lag (its hop budget) — see
    :meth:`FaultPlan.hit_shard`.  No plan armed => one global load."""
    plan = _plan
    if plan is None:
        return None
    return plan.hit_shard(point, max_wait=max_wait)


def check_shard(point: str, shard: int) -> None:
    """Shard-scoped instrumented boundary (``store.shard_down`` /
    ``wal.torn``): a rule carrying ``shard=I`` fires only for calls
    addressing that shard — how a chaos plan takes down ONE shard of
    the sharded event store while its siblings keep accepting.  A rule
    without the option behaves like :func:`check`.  No plan armed =>
    one global load."""
    plan = _plan
    if plan is None:
        return
    plan.hit(point, shard=shard)


def check_tenant(point: str, tenant: str) -> None:
    """Tenant-scoped instrumented boundary (``tenant.dispatch``): a
    rule carrying ``tenant=app/variant`` fires only for that tenant's
    calls — how a chaos plan opens ONE tenant's breaker while its
    neighbors keep serving.  A rule without the option behaves like
    :func:`check`.  No plan armed => one global load."""
    plan = _plan
    if plan is None:
        return
    plan.hit(point, tenant=tenant)


def fired(point: str) -> bool:
    """Ask-style boolean consultation for points whose degradation is
    an in-band state change rather than an exception or a shard id
    (``train.nan``: the sweep loop poisons its own factors when the
    rule fires).  Counts the call and applies any rule delay; no plan
    armed => one global load."""
    plan = _plan
    if plan is None:
        return False
    return plan.hit_shard(point) is not None


# operator workflow: arm from the environment at import, so any entry
# point (CLI deploy/eventserver, a test subprocess) picks the plan up
# without code changes
_env_spec = os.environ.get("PIO_FAULT_PLAN")
if _env_spec:
    arm(_env_spec)
del _env_spec
