"""Deterministic fault injection: named points in the real code paths.

Production code calls :func:`check` at each named boundary; with no
plan armed that is one module-global load and a ``None`` compare — the
happy path costs nothing.  Tests (or an operator reproducing an
incident) arm a plan programmatically or through the ``PIO_FAULT_PLAN``
environment variable and the *real* serving/ingestion/restore code
executes its degradation paths.

Injection points instrumented in this codebase::

    storage.write      event-server storage inserts
    storage.read       event-server storage scans
    device.dispatch    serving predict just before the device call
    http.feedback      feedback-event delivery (delivery queue send)
    http.remote_log    remote error-log delivery (delivery queue send)
    reload.load_model  engine (re)load of trained components

Plan grammar (``;``-separated rules, ``,``-separated options)::

    PIO_FAULT_PLAN="storage.write:nth=1,times=2,exc=operational"
    PIO_FAULT_PLAN="seed=7;http.feedback:prob=0.5;device.dispatch:delay=0.05"

Options per rule:

* ``nth=N``   — first firing call (1-based, default 1)
* ``times=T`` — stop after T firings (default: unlimited)
* ``prob=P``  — fire each eligible call with probability P from a
  seeded per-point RNG (same plan + seed => same firing sequence)
* ``delay=S`` — sleep S seconds when firing (without ``exc``: a pure
  slowdown, the way to exercise deadlines)
* ``exc=NAME`` — exception to raise: ``fault`` (default,
  :class:`InjectedFault`), ``operational`` (sqlite3.OperationalError),
  ``oserror``, ``timeout``, ``urlerror``
"""

from __future__ import annotations

import os
import random
import sqlite3
import threading
import time
import urllib.error
from typing import Optional

__all__ = ["InjectedFault", "FaultRule", "FaultPlan", "POINTS",
           "arm", "disarm", "armed", "check"]

POINTS = (
    "storage.write",
    "storage.read",
    "device.dispatch",
    "http.feedback",
    "http.remote_log",
    "reload.load_model",
)


class InjectedFault(RuntimeError):
    """The default exception a firing injection point raises."""


def _make_exc(name: str, msg: str) -> BaseException:
    if name == "fault":
        return InjectedFault(msg)
    if name == "operational":
        return sqlite3.OperationalError(msg)
    if name == "oserror":
        return OSError(msg)
    if name == "timeout":
        return TimeoutError(msg)
    if name == "urlerror":
        return urllib.error.URLError(msg)
    raise ValueError(f"unknown fault exception kind {name!r}")


class FaultRule:
    def __init__(self, point: str, nth: int = 1,
                 times: Optional[int] = None, prob: Optional[float] = None,
                 delay: Optional[float] = None, exc: Optional[str] = None,
                 seed: Optional[int] = None):
        if point not in POINTS:
            raise ValueError(
                f"unknown injection point {point!r}; known: {POINTS}"
            )
        if exc is not None:
            _make_exc(exc, "probe")  # validate the name at parse time
        self.point = point
        self.nth = nth
        self.times = times
        self.prob = prob
        self.delay = delay
        # a pure-delay rule raises nothing; otherwise default InjectedFault
        self.exc = exc if exc is not None else (
            None if delay is not None else "fault"
        )
        # per-point RNG stream: a rule's firing sequence depends only on
        # its own call order, not on when OTHER points were checked
        self._rng = random.Random(f"{seed}:{point}")
        self.calls = 0
        self.fires = 0

    def hit(self) -> tuple[bool, Optional[BaseException]]:
        """Count one call; decide whether this call fires and what (if
        anything) to raise.  Caller holds the plan lock."""
        self.calls += 1
        if self.calls < self.nth:
            return False, None
        if self.times is not None and self.fires >= self.times:
            return False, None
        if self.prob is not None and self._rng.random() >= self.prob:
            return False, None
        self.fires += 1
        exc = None if self.exc is None else _make_exc(
            self.exc,
            f"injected fault at {self.point} (call {self.calls})",
        )
        return True, exc


class FaultPlan:
    """A set of rules, at most one per point, plus the firing log."""

    def __init__(self, rules: list[FaultRule]):
        self._rules = {r.point: r for r in rules}
        self._lock = threading.Lock()
        # (point, call_index) per firing — the observable sequence a
        # determinism test compares across identically-seeded runs
        self.log: list[tuple[str, int]] = []

    @classmethod
    def parse(cls, spec: str, seed: Optional[int] = None) -> "FaultPlan":
        rules = []
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            if ":" not in part:
                k, _, v = part.partition("=")
                if k.strip() != "seed":
                    raise ValueError(f"bad fault rule {part!r}")
                seed = int(v)
                continue
            point, _, opts = part.partition(":")
            kw: dict = {}
            for opt in opts.split(","):
                if not opt.strip():
                    continue
                k, _, v = opt.partition("=")
                k = k.strip()
                if k in ("nth", "times"):
                    kw[k] = int(v)
                elif k in ("prob", "delay"):
                    kw[k] = float(v)
                elif k == "exc":
                    kw[k] = v.strip()
                elif k == "seed":
                    kw[k] = int(v)
                else:
                    raise ValueError(f"unknown fault option {k!r}")
            kw.setdefault("seed", seed)
            rules.append(FaultRule(point.strip(), **kw))
        return cls(rules)

    def hit(self, point: str) -> None:
        rule = self._rules.get(point)
        if rule is None:
            return
        with self._lock:
            fired, exc = rule.hit()
            if fired:
                self.log.append((point, rule.calls))
        if not fired:
            return
        if rule.delay:
            time.sleep(rule.delay)  # outside the lock: other points flow
        if exc is not None:
            raise exc

    def counters(self) -> dict:
        with self._lock:
            return {
                p: {"calls": r.calls, "fires": r.fires}
                for p, r in self._rules.items()
            }


_plan: Optional[FaultPlan] = None


def arm(plan_or_spec, seed: Optional[int] = None) -> FaultPlan:
    """Activate a plan (replacing any armed one) and return it."""
    global _plan
    plan = (plan_or_spec if isinstance(plan_or_spec, FaultPlan)
            else FaultPlan.parse(plan_or_spec, seed=seed))
    _plan = plan
    return plan


def disarm() -> None:
    global _plan
    _plan = None


def armed() -> Optional[FaultPlan]:
    return _plan


def check(point: str) -> None:
    """The instrumented boundary.  No plan armed => one global load."""
    plan = _plan
    if plan is None:
        return
    plan.hit(point)


# operator workflow: arm from the environment at import, so any entry
# point (CLI deploy/eventserver, a test subprocess) picks the plan up
# without code changes
_env_spec = os.environ.get("PIO_FAULT_PLAN")
if _env_spec:
    arm(_env_spec)
del _env_spec
