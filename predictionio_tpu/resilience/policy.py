"""Retry, deadline, and circuit-breaker primitives.

Pure stdlib, no package-internal imports — every other layer (server,
storage, workflow) may depend on this module without cycles.
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
from typing import Any, Callable, Iterator, Optional

__all__ = [
    "RetryPolicy",
    "Deadline",
    "DeadlineExceeded",
    "CircuitBreaker",
    "deadline_scope",
    "current_deadline",
    "check_deadline",
]


class RetryPolicy:
    """Exponential backoff with decorrelated jitter.

    ``max_attempts`` counts the first try: 3 means one call plus up to
    two retries.  Delays follow the decorrelated-jitter scheme
    (AWS architecture blog): ``d_0 = base``, ``d_n = min(cap,
    uniform(base, 3 * d_{n-1}))`` — successive waiters spread out
    instead of thundering back in lockstep.  A ``seed`` pins the jitter
    RNG so a fault-injection test observes the exact same delay
    sequence on every run.
    """

    def __init__(self, max_attempts: int = 3, base_s: float = 0.05,
                 cap_s: float = 2.0, seed: Optional[int] = None):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = max_attempts
        self.base_s = base_s
        self.cap_s = cap_s
        self.seed = seed
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    def delays(self) -> Iterator[float]:
        """The backoff sequence for one call: ``max_attempts - 1``
        sleeps."""
        prev = self.base_s
        for _ in range(self.max_attempts - 1):
            with self._lock:  # Random() is not thread-safe for streams
                d = min(self.cap_s,
                        self._rng.uniform(self.base_s, prev * 3))
            prev = max(d, self.base_s)
            yield d

    def backoff(self, attempt: int) -> float:
        """Stateless jittered delay for a caller tracking its own
        attempt count (attempt 1 = first failure), for consumers like
        the delivery drain thread whose retries interleave across many
        queued entries."""
        hi = min(self.cap_s, self.base_s * (3 ** max(0, attempt - 1)))
        with self._lock:
            return self._rng.uniform(self.base_s, max(self.base_s, hi))

    def call(
        self,
        fn: Callable[[], Any],
        retry_on: tuple = (Exception,),
        sleep: Callable[[float], None] = time.sleep,
        on_retry: Optional[Callable[[int, BaseException], None]] = None,
    ) -> Any:
        """Run ``fn`` with retries on ``retry_on`` exceptions.

        The final failure re-raises unwrapped, so callers' existing
        except clauses keep working.  A deadline in scope bounds the
        whole retry loop: once the budget cannot cover the next sleep,
        the last error surfaces instead of sleeping past it.
        """
        delays = self.delays()
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn()
            except retry_on as e:
                d = next(delays, None)
                if d is None:
                    raise
                dl = current_deadline()
                if dl is not None and dl.remaining() <= d:
                    raise
                if on_retry is not None:
                    on_retry(attempt, e)
                sleep(d)


class DeadlineExceeded(TimeoutError):
    """A propagated time budget ran out before the operation finished."""


class Deadline:
    """A fixed point in (monotonic) time that work must finish by.

    Created once per request and consulted at the expensive boundaries
    (storage access, device dispatch) so an overloaded server answers a
    structured 503 instead of queueing unbounded work behind a client
    that already gave up.
    """

    __slots__ = ("expires_at", "budget_s", "_clock")

    def __init__(self, budget_s: float,
                 clock: Callable[[], float] = time.monotonic):
        self.budget_s = budget_s
        self._clock = clock
        self.expires_at = clock() + budget_s

    @classmethod
    def after(cls, budget_s: float,
              clock: Callable[[], float] = time.monotonic) -> "Deadline":
        return cls(budget_s, clock)

    def remaining(self) -> float:
        return self.expires_at - self._clock()

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0

    def check(self, what: str = "operation") -> None:
        if self.expired:
            raise DeadlineExceeded(
                f"{what} exceeded its {self.budget_s:.3f}s deadline"
            )


_scope = threading.local()


@contextlib.contextmanager
def deadline_scope(deadline: Optional[Deadline]):
    """Propagate ``deadline`` to everything on this thread inside the
    scope (storage methods call :func:`check_deadline` without any
    plumbing through intermediate signatures).  ``None`` is a no-op
    scope so call sites don't need to branch."""
    prev = getattr(_scope, "deadline", None)
    _scope.deadline = deadline if deadline is not None else prev
    try:
        yield deadline
    finally:
        _scope.deadline = prev


def current_deadline() -> Optional[Deadline]:
    return getattr(_scope, "deadline", None)


def check_deadline(what: str = "operation") -> None:
    """Raise :class:`DeadlineExceeded` if the scope's budget ran out.
    One thread-local read when no deadline is set — cheap enough for
    per-call placement on hot paths."""
    dl = getattr(_scope, "deadline", None)
    if dl is not None:
        dl.check(what)


class CircuitBreaker:
    """Closed / open / half-open breaker for one dependency.

    After ``failure_threshold`` consecutive failures the breaker opens:
    :meth:`allow` answers False (callers skip the doomed I/O) until
    ``reset_timeout_s`` elapses, then exactly one probe is let through
    (half-open).  A probe success closes the breaker; a probe failure
    re-opens it for another timeout.  This is what stops a dead event
    server or log collector from consuming a send attempt (and its
    timeout) per request forever.
    """

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half-open"

    def __init__(self, failure_threshold: int = 5,
                 reset_timeout_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self.open_count = 0  # lifetime transitions into OPEN

    @property
    def state(self) -> str:
        with self._lock:
            return self._peek_state()

    def _peek_state(self) -> str:
        # lock held; does NOT claim the half-open probe slot
        if (self._state == self.OPEN
                and self._clock() - self._opened_at >= self.reset_timeout_s):
            return self.HALF_OPEN
        return self._state

    def allow(self) -> bool:
        """Whether a call may proceed now.  In the open state, the
        first allow() after the reset timeout claims the single
        half-open probe; concurrent callers keep getting False until
        the probe reports."""
        with self._lock:
            if self._state == self.CLOSED:
                return True
            if self._state == self.OPEN:
                if self._clock() - self._opened_at >= self.reset_timeout_s:
                    self._state = self.HALF_OPEN
                    return True
                return False
            return False  # HALF_OPEN: probe already in flight

    def record_success(self) -> None:
        with self._lock:
            self._state = self.CLOSED
            self._consecutive_failures = 0

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive_failures += 1
            if (self._state == self.HALF_OPEN
                    or self._consecutive_failures >= self.failure_threshold):
                if self._state != self.OPEN:
                    self.open_count += 1
                self._state = self.OPEN
                self._opened_at = self._clock()

    def snapshot(self) -> dict:
        """Status-JSON view."""
        with self._lock:
            return {
                "state": self._peek_state(),
                "consecutiveFailures": self._consecutive_failures,
                "openCount": self.open_count,
                "failureThreshold": self.failure_threshold,
                "resetTimeoutSec": self.reset_timeout_s,
            }
