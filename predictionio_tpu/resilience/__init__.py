"""Resilience layer: deadlines, retry/backoff, circuit breakers, a
bounded background delivery queue, and a deterministic fault-injection
registry.

The reference deploys long-running serving + event-server processes
whose failure handling is implicit (a dead log collector must not stall
serving — `CreateServer.scala` fires feedback/remoteLog asynchronously
for exactly that reason).  At "millions of users" scale failures are the
steady state, so the machinery is explicit here:

* :mod:`.policy` — :class:`RetryPolicy` (exponential backoff with
  decorrelated jitter, seeded so tests are deterministic),
  :class:`Deadline` (a propagated time budget checked at storage and
  device-dispatch boundaries), :class:`CircuitBreaker`
  (closed/open/half-open per dependency).
* :mod:`.faults` — named injection points instrumented in the real
  code paths, armed programmatically or via ``PIO_FAULT_PLAN``;
  zero overhead when no plan is armed.
* :mod:`.delivery` — a bounded single-drain-thread delivery queue
  (retry with backoff, drop-oldest when full) replacing
  thread-per-request fire-and-forget HTTP delivery.
"""

from .policy import (
    CircuitBreaker,
    Deadline,
    DeadlineExceeded,
    RetryPolicy,
    check_deadline,
    current_deadline,
    deadline_scope,
)
from .faults import (
    FaultPlan,
    InjectedFault,
    arm,
    armed,
    check,
    disarm,
    fired_shard,
)
from .delivery import DeliveryQueue

__all__ = [
    "CircuitBreaker",
    "Deadline",
    "DeadlineExceeded",
    "DeliveryQueue",
    "FaultPlan",
    "InjectedFault",
    "RetryPolicy",
    "arm",
    "armed",
    "check",
    "check_deadline",
    "current_deadline",
    "deadline_scope",
    "disarm",
    "fired_shard",
]
