"""Mesh collectives — the distributed communication backend.

The reference's communication substrate is Spark's shuffle/broadcast
fabric (SURVEY §2.7); the TPU-native equivalent is XLA collectives over
ICI (within a slice) and DCN (across slices), expressed with ``shard_map``
over a `Mesh`.  These wrappers give the framework's runtime and engine
code named, tested entry points for the four primitives the training and
scoring paths use — all-reduce (gradient/stat sums), all-gather (factor
blocks), reduce-scatter (sharded updates), and ring permute (block
rotation) — instead of scattering raw ``jax.lax`` calls around.

Everything here is jit-compatible and works identically on a virtual CPU
mesh (tests) and a TPU pod slice.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import inspect

if hasattr(jax, "shard_map"):            # jax >= 0.8
    _shard_map_impl = jax.shard_map
else:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map as _shard_map_impl

# collective outputs (psum/all_gather) are replicated in ways the static
# checker can't always infer; disable it under whichever flag name this
# jax spells it
_CHECK_FLAG = (
    "check_vma"
    if "check_vma" in inspect.signature(_shard_map_impl).parameters
    else "check_rep"
)


def shard_map(f=None, **kw):
    kw.setdefault(_CHECK_FLAG, False)
    if f is None:
        return functools.partial(_shard_map_impl, **kw)
    return _shard_map_impl(f, **kw)

from .mesh import DATA_AXIS

__all__ = [
    "all_reduce_sum",
    "all_gather_blocks",
    "all_to_all_blocks",
    "reduce_scatter_sum",
    "ring_shift",
]


def all_reduce_sum(x: jax.Array, mesh: Mesh, axis: str = DATA_AXIS):
    """Sum a data-sharded array's shards: [N, ...] sharded -> same value
    replicated on every device (the ``psum`` of a per-shard partial)."""

    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(axis), out_specs=P(),
    )
    def _sum(shard):
        return jax.lax.psum(jnp.sum(shard, axis=0, keepdims=True), axis)

    return _sum(x)[0]


def all_gather_blocks(x: jax.Array, mesh: Mesh, axis: str = DATA_AXIS):
    """Gather a sharded leading dim onto every device (factor-block
    exchange): [N/d per device] -> [N] replicated."""

    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(axis), out_specs=P(),
    )
    def _gather(shard):
        return jax.lax.all_gather(shard, axis, tiled=True)

    return _gather(x)


def all_to_all_blocks(x: jax.Array, mesh: Mesh, axis: str = DATA_AXIS):
    """Shard-transpose: device i's j-th block becomes device j's i-th
    block (the shuffle primitive — Spark's repartition-by-key fabric
    collapsed to one XLA collective over ICI).

    ``x`` is sharded on its leading dim, and each device's shard is
    itself organized as ``d`` destination blocks: ``[d*B, ...]`` sharded
    -> ``[d*B, ...]`` sharded, where the returned device-j shard is
    ``concat(block j of every device i, over i)``.  This is the device-
    side form of the owner-exchange the multi-host ingest does over the
    wire (`parallel/ingest.exchange_ratings_by_owner`): rows grouped by
    owning shard on the way in, landing grouped by origin on the way
    out.  Block sizes must be equal (pad the trailing block — the same
    contract as the host exchange)."""
    d = mesh.shape[axis]
    if x.shape[0] % (d * d) != 0:
        raise ValueError(
            f"all_to_all_blocks needs leading dim divisible by "
            f"mesh_size^2 = {d * d} (d equal blocks per device shard); "
            f"got shape {x.shape}"
        )

    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
    )
    def _a2a(shard):  # [d*B, ...] per device
        blocks = shard.reshape((d, shard.shape[0] // d) + shard.shape[1:])
        out = jax.lax.all_to_all(
            blocks, axis, split_axis=0, concat_axis=0, tiled=False
        )
        return out.reshape(shard.shape)

    return _a2a(x)


def reduce_scatter_sum(x: jax.Array, mesh: Mesh, axis: str = DATA_AXIS):
    """Per-device partials [d, M, ...] (sharded on dim 0) -> the summed
    [M, ...] sharded over the mesh: each device keeps only the slice of
    the sum it owns (the memory-efficient half of an all-reduce)."""
    d = mesh.shape[axis]
    if x.shape[0] != d:
        raise ValueError(
            f"reduce_scatter_sum expects leading dim == mesh axis size "
            f"{d} (one partial per device); got shape {x.shape}"
        )

    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
    )
    def _scatter(partial):  # [1, M, ...] per device
        return jax.lax.psum_scatter(
            partial[0], axis, scatter_dimension=0, tiled=True
        )

    return _scatter(x)


def ring_shift(x: jax.Array, mesh: Mesh, axis: str = DATA_AXIS, shift: int = 1):
    """Rotate shards around the mesh ring (block-cyclic ALS-style
    exchange): shard i -> device (i + shift) mod d."""
    n_dev = mesh.shape[axis]
    perm = [(i, (i + shift) % n_dev) for i in range(n_dev)]

    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
    )
    def _shift(shard):
        return jax.lax.ppermute(shard, axis, perm)

    return _shift(x)
