"""Multi-host sharded event ingest (SURVEY §7 hard part 4).

The reference reads training data region-parallel from HBase: every Spark
executor scans its table regions and the driver never materializes the full
event set in one process (`/root/reference/data/src/main/scala/io/prediction/
data/storage/hbase/HBPEvents.scala:99-105`).  The TPU-native equivalent for a
`jax.distributed` multi-process run:

* **Shard the scan by entity hash** — each process calls
  :func:`find_columnar_sharded` and receives only the events whose
  ``entity_id`` hashes into its shard (stable MD5-based hash, the analogue of
  the reference's ``MD5(entityType-entityId)`` row-key prefix,
  `storage/hbase/HBEventsUtil.scala:74-129`).  A user's events always land on
  one process, so per-row preprocessing (rating dedup, bucket grouping) stays
  process-local.
* **Build one global id dictionary** — string ids can't ride XLA
  collectives; processes exchange their local unique ids through the shared
  storage directory (the role HDFS played for the reference) and everyone
  deterministically builds the same sorted-unique :class:`StringIndex`
  (`ids_exchange`).
* **All-gather the numeric COO** — once encoded against the global index,
  the int/float rating triples are exchanged with a padded
  ``process_allgather`` so every process holds the full training COO
  (`gather_ratings`), which the replicated-COO ALS path consumes directly;
  the factor tables themselves can stay sharded (``factor_placement=
  "sharded"``).

Single-process runs short-circuit: shard 0 of 1 is the whole table and the
gathers are identity.
"""

from __future__ import annotations

import hashlib
import time
from pathlib import Path
from typing import Optional, Sequence

import numpy as np

__all__ = [
    "entity_shard",
    "shard_mask",
    "find_columnar_sharded",
    "ids_exchange",
    "gather_ratings",
    "read_ratings_distributed",
]


def entity_shard(entity_id: str, n_shards: int) -> int:
    """Stable shard of an entity id (md5-based, like the reference's
    event row key `storage/hbase/HBEventsUtil.scala:96-128`)."""
    h = hashlib.md5(entity_id.encode("utf-8")).digest()
    return int.from_bytes(h[:8], "big") % n_shards


def shard_mask(entity_ids: np.ndarray, n_shards: int, shard_id: int) -> np.ndarray:
    """Boolean mask of the rows whose entity hashes into ``shard_id``.

    Hashes each UNIQUE id once and scatters by inverse index: event tables
    have many rows per entity, so hashing per-row would repeat the
    interpreter-level md5 N/U times for nothing.
    """
    if n_shards <= 1:
        return np.ones(len(entity_ids), dtype=bool)
    uniq, inv = np.unique(np.asarray(entity_ids), return_inverse=True)
    owners = np.fromiter(
        (entity_shard(str(e), n_shards) for e in uniq),
        dtype=np.int64,
        count=len(uniq),
    )
    return owners[inv] == shard_id


def find_columnar_sharded(
    es,
    n_shards: int,
    shard_id: int,
    **kwargs,
):
    """This process's shard of a columnar event scan.

    Generic implementation: full backend scan + entity-hash filter.  (A
    backend could push the predicate down; correctness is identical and the
    scan stays embarrassingly parallel either way.)
    """
    if not 0 <= shard_id < max(n_shards, 1):
        raise ValueError(f"shard_id {shard_id} out of range 0..{n_shards - 1}")
    frame = es.find_columnar(**kwargs)
    if n_shards <= 1:
        return frame
    return frame.select(shard_mask(frame.entity_id, n_shards, shard_id))


# --------------------------------------------------------------------------
# Global id dictionary via shared-directory exchange
# --------------------------------------------------------------------------

_STALE_AGE_S = 3600.0


def _sweep_stale(exchange_dir: Path, age_s: float = _STALE_AGE_S) -> None:
    """Best-effort removal of exchange files no live run can still want.

    Files from a crashed run (SIGKILL between publish and cleanup) or from
    explicit-topology callers (who have no barrier to clean up behind) are
    nonce- or caller-tagged and will never be matched again; anything older
    than ``age_s`` is dead weight in the shared storage tree.  Live runs
    are unaffected: their files are seconds old.
    """
    cutoff = time.time() - age_s
    try:
        for f in exchange_dir.glob("*.npz"):
            try:
                if f.stat().st_mtime < cutoff:
                    f.unlink(missing_ok=True)
            except OSError:
                continue
    except OSError:
        pass


def ids_exchange(
    local_ids: Sequence[str],
    exchange_dir,
    tag: str,
    process_id: Optional[int] = None,
    process_count: Optional[int] = None,
    timeout: float = 120.0,
):
    """All processes contribute their local unique ids; everyone gets the
    same deterministic global :class:`StringIndex` (sorted unique union).

    Exchange rides the shared storage directory (every multi-host
    PredictionIO deployment shares its storage tree, as the reference shared
    HDFS/HBase); files are written atomically and polled with a timeout, so
    no collective is needed for the string payload.

    When the process topology comes from ``jax.distributed`` (the default —
    ``process_id``/``process_count`` not given), the call is self-protecting
    against stale files: a run nonce broadcast from process 0 is folded into
    the file names, and every process deletes its own file after a global
    sync, so shard files left behind by a crashed earlier run with the same
    ``tag`` can never be merged into this run's union.  Callers that pass an
    explicit ``process_id``/``process_count`` (no jax.distributed to ride)
    must guarantee tag uniqueness per run themselves.
    """
    import jax

    from ..storage.bimap import StringIndex

    managed = process_id is None and process_count is None
    pid = jax.process_index() if process_id is None else process_id
    n = jax.process_count() if process_count is None else process_count
    if n <= 1:
        return StringIndex.from_values(local_ids)
    if managed:
        import secrets

        from jax.experimental import multihost_utils

        nonce = int(
            multihost_utils.broadcast_one_to_all(
                np.int64(secrets.randbits(62))
            )
        )
        tag = f"{tag}-{nonce:016x}"

    exchange_dir = Path(exchange_dir)
    exchange_dir.mkdir(parents=True, exist_ok=True)
    # never sweep inside the window a live straggler could still publish in
    _sweep_stale(exchange_dir, age_s=max(_STALE_AGE_S, 2.0 * timeout))
    mine = exchange_dir / f"{tag}-{pid}.npz"
    # keep the .npz suffix on the temp name: np.savez appends it otherwise
    tmp = exchange_dir / f"{tag}-{pid}.tmp.npz"
    np.savez_compressed(
        tmp, ids=np.asarray(sorted(set(local_ids)), dtype=str)
    )
    tmp.rename(mine)  # atomic publish

    union: set[str] = set()
    deadline = time.time() + timeout
    try:
        for other in range(n):
            path = exchange_dir / f"{tag}-{other}.npz"
            while not path.exists():
                if time.time() > deadline:
                    raise TimeoutError(
                        f"ids_exchange: shard file {path} not published "
                        f"within {timeout}s"
                    )
                time.sleep(0.05)
            data = np.load(path, allow_pickle=False)
            union.update(data["ids"].tolist())
    except BaseException:
        # failed exchange: withdraw this process's file so it can't be
        # merged into (or leak from) a later run
        mine.unlink(missing_ok=True)
        raise
    if managed:
        # everyone has read every shard file; drop this process's own
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"ids-exchange-{tag}")
        mine.unlink(missing_ok=True)
    return StringIndex.from_values(union)


# --------------------------------------------------------------------------
# Numeric COO all-gather
# --------------------------------------------------------------------------


def _allgather_padded(arr: np.ndarray) -> np.ndarray:
    """Concatenate a per-process 1-D array across processes (uneven sizes:
    pad to the max, gather, trim)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    n = jax.process_count()
    count = np.asarray([len(arr)], dtype=np.int32)
    counts = np.asarray(
        multihost_utils.process_allgather(count)
    ).reshape(n)
    cap = int(counts.max())
    padded = np.zeros(cap, dtype=arr.dtype)
    padded[: len(arr)] = arr
    gathered = np.asarray(
        multihost_utils.process_allgather(jnp.asarray(padded))
    ).reshape(n, cap)
    return np.concatenate([gathered[p, : counts[p]] for p in range(n)])


def gather_ratings(ratings):
    """Union of every process's COO shard, on every process.

    Requires the shards to be encoded against the SAME global id index
    (see :func:`ids_exchange`); single-process is the identity.
    """
    import jax

    from ..storage.columnar import Ratings

    if jax.process_count() <= 1:
        return ratings
    return Ratings(
        user_ix=_allgather_padded(ratings.user_ix),
        item_ix=_allgather_padded(ratings.item_ix),
        rating=_allgather_padded(ratings.rating),
        users=ratings.users,
        items=ratings.items,
    )


def read_ratings_distributed(
    es,
    exchange_dir,
    tag: str = "ratings",
    rating_property: Optional[str] = None,
    dedup: str = "last",
    **scan_kwargs,
):
    """End-to-end multi-host training-data read: sharded scan -> global id
    dictionaries -> globally-encoded COO -> all-gathered ratings.

    Single-process: equivalent to ``es.find_columnar(...).to_ratings(...)``.
    """
    import jax

    n, pid = jax.process_count(), jax.process_index()
    frame = find_columnar_sharded(
        es, n_shards=n, shard_id=pid,
        float_property=rating_property,
        minimal=True,   # only to_ratings fields are consumed downstream
        **scan_kwargs,
    )
    # ids_exchange self-protects against stale files (per-run nonce +
    # post-sync cleanup) on the jax-managed path used here
    users = ids_exchange(
        frame.entity_id.tolist(), exchange_dir, f"{tag}-users"
    )
    items = ids_exchange(
        frame.target_entity_id.tolist(), exchange_dir, f"{tag}-items"
    )
    local = frame.to_ratings(
        rating_property=rating_property,
        user_index=users,
        item_index=items,
        dedup=dedup,
    )
    return gather_ratings(local)
