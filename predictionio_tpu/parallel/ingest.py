"""Multi-host sharded event ingest (SURVEY §7 hard part 4).

The reference reads training data region-parallel from HBase: every Spark
executor scans its table regions and the driver never materializes the full
event set in one process (`/root/reference/data/src/main/scala/io/prediction/
data/storage/hbase/HBPEvents.scala:99-105`).  The TPU-native equivalent for a
`jax.distributed` multi-process run:

* **Shard the scan by entity hash** — each process calls
  :func:`find_columnar_sharded` and receives only the events whose
  ``entity_id`` hashes into its shard (stable MD5-based hash, the analogue of
  the reference's ``MD5(entityType-entityId)`` row-key prefix,
  `storage/hbase/HBEventsUtil.scala:74-129`).  A user's events always land on
  one process, so per-row preprocessing (rating dedup, bucket grouping) stays
  process-local.
* **Build one global id dictionary** — string ids can't ride XLA
  collectives; processes exchange their local unique ids through the shared
  storage directory (the role HDFS played for the reference) and everyone
  deterministically builds the same sorted-unique :class:`StringIndex`
  (`ids_exchange`).
* **Exchange the numeric COO by row owner** — once encoded against the
  global index, each rating triple is sent to the process whose mesh
  devices solve its row (`exchange_ratings_by_owner`, a file-based
  all-to-all riding the shared storage tree), so NO process ever
  materializes the full COO and rating capacity scales with cluster
  memory — the multi-host face of the sharded-COO layout
  (`models/als._plan_shard_layout`).  The legacy `gather_ratings`
  all-gather remains for the replicated-COO path (small datasets).

Single-process runs short-circuit: shard 0 of 1 is the whole table and the
gathers are identity.
"""

from __future__ import annotations

import hashlib
import logging
import time
from pathlib import Path
from typing import Optional, Sequence

import numpy as np

logger = logging.getLogger(__name__)

__all__ = [
    "entity_shard",
    "shard_mask",
    "find_columnar_sharded",
    "ids_exchange",
    "gather_ratings",
    "exchange_ratings_by_owner",
    "read_ratings_distributed",
    "distributed_trainer",
    "ExchangeTornError",
]


class ExchangeTornError(RuntimeError):
    """The sharded-COO file exchange failed past its retry budget.

    Raised by :func:`exchange_ratings_by_owner` after the configured
    retries; :func:`distributed_trainer` catches it and degrades to the
    replicated gather path (correct, but rating memory no longer scales
    with the cluster) instead of dying mid-train."""


def entity_shard(entity_id: str, n_shards: int) -> int:
    """Stable shard of an entity id (md5-based, like the reference's
    event row key `storage/hbase/HBEventsUtil.scala:96-128`)."""
    h = hashlib.md5(entity_id.encode("utf-8")).digest()
    return int.from_bytes(h[:8], "big") % n_shards


def shard_mask(entity_ids: np.ndarray, n_shards: int, shard_id: int) -> np.ndarray:
    """Boolean mask of the rows whose entity hashes into ``shard_id``.

    Hashes each UNIQUE id once and scatters by inverse index: event tables
    have many rows per entity, so hashing per-row would repeat the
    interpreter-level md5 N/U times for nothing.
    """
    if n_shards <= 1:
        return np.ones(len(entity_ids), dtype=bool)
    uniq, inv = np.unique(np.asarray(entity_ids), return_inverse=True)
    owners = np.fromiter(
        (entity_shard(str(e), n_shards) for e in uniq),
        dtype=np.int64,
        count=len(uniq),
    )
    return owners[inv] == shard_id


def find_columnar_sharded(
    es,
    n_shards: int,
    shard_id: int,
    **kwargs,
):
    """This process's shard of a columnar event scan.

    Generic implementation: full backend scan + entity-hash filter.  (A
    backend could push the predicate down; correctness is identical and the
    scan stays embarrassingly parallel either way.)
    """
    if not 0 <= shard_id < max(n_shards, 1):
        raise ValueError(f"shard_id {shard_id} out of range 0..{n_shards - 1}")
    frame = es.find_columnar(**kwargs)
    if n_shards <= 1:
        return frame
    return frame.select(shard_mask(frame.entity_id, n_shards, shard_id))


# --------------------------------------------------------------------------
# Global id dictionary via shared-directory exchange
# --------------------------------------------------------------------------

_STALE_AGE_S = 3600.0


def _sweep_stale(exchange_dir: Path, age_s: float = _STALE_AGE_S) -> None:
    """Best-effort removal of exchange files no live run can still want.

    Files from a crashed run (SIGKILL between publish and cleanup) or from
    explicit-topology callers (who have no barrier to clean up behind) are
    nonce- or caller-tagged and will never be matched again; anything older
    than ``age_s`` is dead weight in the shared storage tree.  Live runs
    are unaffected: their files are seconds old.
    """
    cutoff = time.time() - age_s
    try:
        for f in exchange_dir.glob("*.npz"):
            try:
                if f.stat().st_mtime < cutoff:
                    f.unlink(missing_ok=True)
            except OSError:
                continue
    except OSError:
        pass


def ids_exchange(
    local_ids: Sequence[str],
    exchange_dir,
    tag: str,
    process_id: Optional[int] = None,
    process_count: Optional[int] = None,
    timeout: float = 120.0,
):
    """All processes contribute their local unique ids; everyone gets the
    same deterministic global :class:`StringIndex` (sorted unique union).

    Exchange rides the shared storage directory (every multi-host
    PredictionIO deployment shares its storage tree, as the reference shared
    HDFS/HBase); files are written atomically and polled with a timeout, so
    no collective is needed for the string payload.

    When the process topology comes from ``jax.distributed`` (the default —
    ``process_id``/``process_count`` not given), the call is self-protecting
    against stale files: a run nonce broadcast from process 0 is folded into
    the file names, and every process deletes its own file after a global
    sync, so shard files left behind by a crashed earlier run with the same
    ``tag`` can never be merged into this run's union.  Callers that pass an
    explicit ``process_id``/``process_count`` (no jax.distributed to ride)
    must guarantee tag uniqueness per run themselves.
    """
    import jax

    from ..storage.bimap import StringIndex

    managed = process_id is None and process_count is None
    pid = jax.process_index() if process_id is None else process_id
    n = jax.process_count() if process_count is None else process_count
    if n <= 1:
        return StringIndex.from_values(local_ids)
    if managed:
        import secrets

        from jax.experimental import multihost_utils

        nonce = int(
            multihost_utils.broadcast_one_to_all(
                np.int64(secrets.randbits(62))
            )
        )
        tag = f"{tag}-{nonce:016x}"

    exchange_dir = Path(exchange_dir)
    exchange_dir.mkdir(parents=True, exist_ok=True)
    # never sweep inside the window a live straggler could still publish in
    _sweep_stale(exchange_dir, age_s=max(_STALE_AGE_S, 2.0 * timeout))
    mine = exchange_dir / f"{tag}-{pid}.npz"
    # keep the .npz suffix on the temp name: np.savez appends it otherwise
    tmp = exchange_dir / f"{tag}-{pid}.tmp.npz"
    np.savez_compressed(
        tmp, ids=np.asarray(sorted(set(local_ids)), dtype=str)
    )
    tmp.rename(mine)  # atomic publish

    union: set[str] = set()
    deadline = time.time() + timeout
    try:
        for other in range(n):
            path = exchange_dir / f"{tag}-{other}.npz"
            while not path.exists():
                if time.time() > deadline:
                    raise TimeoutError(
                        f"ids_exchange: shard file {path} not published "
                        f"within {timeout}s"
                    )
                time.sleep(0.05)
            data = np.load(path, allow_pickle=False)
            union.update(data["ids"].tolist())
    except BaseException:
        # failed exchange: withdraw this process's file so it can't be
        # merged into (or leak from) a later run
        mine.unlink(missing_ok=True)
        raise
    if managed:
        # everyone has read every shard file; drop this process's own
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"ids-exchange-{tag}")
        mine.unlink(missing_ok=True)
    return StringIndex.from_values(union)


# --------------------------------------------------------------------------
# Numeric COO all-gather
# --------------------------------------------------------------------------


def _allgather_padded(arr: np.ndarray) -> np.ndarray:
    """Concatenate a per-process 1-D array across processes (uneven sizes:
    pad to the max, gather, trim)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    n = jax.process_count()
    count = np.asarray([len(arr)], dtype=np.int32)
    counts = np.asarray(
        multihost_utils.process_allgather(count)
    ).reshape(n)
    cap = int(counts.max())
    padded = np.zeros(cap, dtype=arr.dtype)
    padded[: len(arr)] = arr
    gathered = np.asarray(
        multihost_utils.process_allgather(jnp.asarray(padded))
    ).reshape(n, cap)
    return np.concatenate([gathered[p, : counts[p]] for p in range(n)])


def gather_ratings(ratings):
    """Union of every process's COO shard, on every process.

    Requires the shards to be encoded against the SAME global id index
    (see :func:`ids_exchange`); single-process is the identity.
    """
    import jax

    from ..storage.columnar import Ratings

    if jax.process_count() <= 1:
        return ratings
    return Ratings(
        user_ix=_allgather_padded(ratings.user_ix),
        item_ix=_allgather_padded(ratings.item_ix),
        rating=_allgather_padded(ratings.rating),
        users=ratings.users,
        items=ratings.items,
    )


def _exchange_all_to_all(
    exchange_dir,
    tag: str,
    payloads: dict[int, dict[str, np.ndarray]],
    timeout: float = 120.0,
) -> list[dict]:
    """File-based all-to-all over the shared storage tree.

    Every process writes one npz per destination process (EVERY
    destination, even when empty — absence must mean "not published yet",
    never "nothing to send") and reads the files addressed to it.  Same
    self-protection as :func:`ids_exchange`: per-run nonce from process 0
    folded into filenames, post-sync cleanup of own files, withdrawal on
    failure.  Returns the loaded dicts ordered by source process.
    """
    import jax
    import secrets

    from jax.experimental import multihost_utils

    pid, n = jax.process_index(), jax.process_count()
    nonce = int(
        multihost_utils.broadcast_one_to_all(np.int64(secrets.randbits(62)))
    )
    tag = f"{tag}-{nonce:016x}"
    exchange_dir = Path(exchange_dir)
    exchange_dir.mkdir(parents=True, exist_ok=True)
    _sweep_stale(exchange_dir, age_s=max(_STALE_AGE_S, 2.0 * timeout))
    from ..resilience import faults

    mine: list[Path] = []
    try:
        for dst in range(n):
            # dist.exchange_torn: a publish tears mid-exchange — the
            # except-path below withdraws the files already published,
            # exactly what a crashed real peer forces survivors to do
            faults.check("dist.exchange_torn")
            path = exchange_dir / f"{tag}-{pid}to{dst}.npz"
            tmp = exchange_dir / f"{tag}-{pid}to{dst}.tmp.npz"
            # uncompressed on purpose: this path exists for bulk numeric
            # COO payloads, where single-threaded deflate would dominate
            # the exchange wall-clock for little ratio (int32/f32 rating
            # triples compress poorly); the small string id exchange
            # keeps compression
            np.savez(tmp, **payloads.get(dst, {}))
            tmp.rename(path)  # atomic publish
            mine.append(path)
        out = []
        deadline = time.time() + timeout
        for src in range(n):
            path = exchange_dir / f"{tag}-{src}to{pid}.npz"
            while not path.exists():
                if time.time() > deadline:
                    raise TimeoutError(
                        f"exchange: shard file {path} not published "
                        f"within {timeout}s"
                    )
                time.sleep(0.05)
            with np.load(path, allow_pickle=False) as data:
                out.append({k: data[k] for k in data.files})
    except BaseException:
        for p in mine:
            p.unlink(missing_ok=True)
        raise
    multihost_utils.sync_global_devices(f"coo-exchange-{tag}")
    for p in mine:
        p.unlink(missing_ok=True)
    return out


def exchange_ratings_by_owner(
    row_ix: np.ndarray,
    col_ix: np.ndarray,
    rating: np.ndarray,
    owner_of_row: np.ndarray,
    exchange_dir,
    tag: str,
    timeout: float = 120.0,
    retry=None,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Send each rating triple to the process owning its ROW and return
    the triples this process received (concatenated over sources).

    ``owner_of_row[r]`` names the destination process of row ``r``
    (derived from the shard plan's row→device map plus the mesh's
    device→process map; called once per ALS side with that side's row
    ids).  The multi-host replacement for :func:`gather_ratings`:
    afterwards each process holds exactly the ratings its devices solve
    — cluster rating capacity scales with the number of hosts instead of
    being capped by one host's memory, the way the reference's
    region-sharded HBase scan never materialized the full event set in
    one JVM (`storage/hbase/HBPEvents.scala:99-105`).

    A torn exchange (``dist.exchange_torn`` injection, a crashed peer's
    half-published files, a flaky shared filesystem) is retried whole —
    each attempt re-publishes under a fresh nonce, so a retry can never
    merge a previous attempt's partial files.  Retries stay in lockstep
    across processes for deterministic (plan-armed) faults because every
    process consults the same ``PIO_FAULT_PLAN``; a genuinely one-sided
    failure surfaces as a timeout on the survivors, which the same
    retry covers.  Past the budget the call raises
    :class:`ExchangeTornError` — callers with a fallback (see
    :func:`distributed_trainer`) degrade instead of hanging.
    """
    import jax

    from ..obs import RESILIENCE_TOTAL
    from ..resilience import RetryPolicy, faults

    n = jax.process_count()
    if retry is None:
        retry = RetryPolicy(max_attempts=2, base_s=0.05, seed=0)

    def attempt():
        # consulted before the single-process short-circuit so the
        # torn-exchange semantics are testable on the simulated cluster
        faults.check("dist.exchange_torn")
        if n <= 1:
            return row_ix, col_ix, rating
        dest = np.asarray(owner_of_row)[row_ix]
        payloads = {}
        for dst in range(n):
            sel = dest == dst
            payloads[dst] = {
                "r": np.ascontiguousarray(row_ix[sel]),
                "c": np.ascontiguousarray(col_ix[sel]),
                "v": np.ascontiguousarray(rating[sel]),
            }
        got = _exchange_all_to_all(
            exchange_dir, tag, payloads, timeout=timeout
        )
        return (
            np.concatenate([g["r"] for g in got]),
            np.concatenate([g["c"] for g in got]),
            np.concatenate([g["v"] for g in got]),
        )

    def on_retry(attempt_no, exc):
        RESILIENCE_TOTAL.labels(kind="dist.exchange_retry").inc()
        logger.warning(
            "COO exchange %s torn (attempt %d: %s); retrying under a "
            "fresh nonce", tag, attempt_no, exc,
        )

    retriable = (faults.InjectedFault, OSError, TimeoutError)
    try:
        return retry.call(attempt, retry_on=retriable, on_retry=on_retry)
    except retriable as e:
        raise ExchangeTornError(
            f"COO exchange {tag!r} failed past its retry budget: {e}"
        ) from e


def read_ratings_distributed(
    es,
    exchange_dir,
    tag: str = "ratings",
    rating_property: Optional[str] = None,
    dedup: str = "last",
    gather: bool = True,
    **scan_kwargs,
):
    """End-to-end multi-host training-data read: sharded scan -> global id
    dictionaries -> globally-encoded COO -> all-gathered ratings.

    ``gather=False`` skips the final all-gather and returns each
    process's LOCAL shard (still encoded against the global id index) —
    the input :meth:`ALSTrainer.distributed` wants, for trains whose
    rating set must never be resident on one host.

    Single-process: equivalent to ``es.find_columnar(...).to_ratings(...)``.
    """
    import jax

    n, pid = jax.process_count(), jax.process_index()
    frame = find_columnar_sharded(
        es, n_shards=n, shard_id=pid,
        float_property=rating_property,
        minimal=True,   # only to_ratings fields are consumed downstream
        **scan_kwargs,
    )
    # ids_exchange self-protects against stale files (per-run nonce +
    # post-sync cleanup) on the jax-managed path used here
    users = ids_exchange(
        frame.entity_id.tolist(), exchange_dir, f"{tag}-users"
    )
    items = ids_exchange(
        frame.target_entity_id.tolist(), exchange_dir, f"{tag}-items"
    )
    local = frame.to_ratings(
        rating_property=rating_property,
        user_index=users,
        item_index=items,
        dedup=dedup,
    )
    return gather_ratings(local) if gather else local


def distributed_trainer(
    es,
    exchange_dir,
    cfg,
    mesh,
    tag: str = "ratings",
    rating_property: Optional[str] = None,
    dedup: str = "last",
    timeout: float = 120.0,
    **scan_kwargs,
):
    """End-to-end multi-host SHARDED training-data read.

    sharded scan -> global id dictionaries -> locally-encoded COO ->
    row-owner exchange -> :class:`~predictionio_tpu.models.als.ALSTrainer`
    over the sharded-COO layout.  Unlike :func:`read_ratings_distributed`
    (which all-gathers the full COO for the replicated path), no process
    ever materializes the full rating set — both host memory and device
    HBM scale with the cluster.  Single-process degenerates to the plain
    trainer.
    """
    import jax

    from ..models.als import ALSTrainer

    n, pid = jax.process_count(), jax.process_index()
    frame = find_columnar_sharded(
        es, n_shards=n, shard_id=pid,
        float_property=rating_property,
        minimal=True,
        **scan_kwargs,
    )
    users = ids_exchange(
        frame.entity_id.tolist(), exchange_dir, f"{tag}-users"
    )
    items = ids_exchange(
        frame.target_entity_id.tolist(), exchange_dir, f"{tag}-items"
    )
    local = frame.to_ratings(
        rating_property=rating_property,
        user_index=users,
        item_index=items,
        dedup=dedup,
    )
    try:
        return ALSTrainer.distributed(
            local, cfg=cfg, mesh=mesh, exchange_dir=exchange_dir,
            tag=f"{tag}-coo", timeout=timeout,
        )
    except ExchangeTornError as e:
        # retry budget spent: degrade LOUDLY to the replicated gather
        # path — the train still completes and the model is identical,
        # but rating memory no longer scales with the cluster, so the
        # degradation is booked where operators look
        import dataclasses

        from ..obs import RESILIENCE_TOTAL

        RESILIENCE_TOTAL.labels(kind="dist.exchange_degraded").inc()
        logger.error(
            "sharded-COO exchange failed past retries (%s); degrading "
            "to the replicated gather path — rating memory will NOT "
            "scale with the cluster this run", e,
        )
        gathered = gather_ratings(local)
        cfg_rep = dataclasses.replace(
            cfg, factor_placement="replicated", coded_shards=False
        )
        return ALSTrainer(gathered, cfg=cfg_rep, mesh=mesh)
