"""Device-mesh parallelism utilities (the Spark-substrate replacement)."""

from .coded import (
    ParityExhausted,
    ShardHealth,
    build_coded_gather,
    build_parity_fn,
)
from .collectives import (
    all_gather_blocks,
    all_reduce_sum,
    reduce_scatter_sum,
    ring_shift,
)
from .ingest import (
    find_columnar_sharded,
    gather_ratings,
    ids_exchange,
    read_ratings_distributed,
)
from .mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    data_sharding,
    distributed_init,
    enable_compilation_cache,
    fence,
    make_mesh,
    pad_to_multiple,
    replicated,
)

__all__ = [
    "ParityExhausted",
    "ShardHealth",
    "build_coded_gather",
    "build_parity_fn",
    "all_gather_blocks",
    "all_reduce_sum",
    "reduce_scatter_sum",
    "ring_shift",
    "find_columnar_sharded",
    "gather_ratings",
    "ids_exchange",
    "read_ratings_distributed",
    "DATA_AXIS",
    "MODEL_AXIS",
    "data_sharding",
    "distributed_init",
    "enable_compilation_cache",
    "fence",
    "make_mesh",
    "pad_to_multiple",
    "replicated",
]
