"""Device-mesh parallelism utilities (the Spark-substrate replacement)."""

from .collectives import (
    all_gather_blocks,
    all_reduce_sum,
    reduce_scatter_sum,
    ring_shift,
)
from .mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    data_sharding,
    distributed_init,
    enable_compilation_cache,
    make_mesh,
    pad_to_multiple,
    replicated,
)

__all__ = [
    "all_gather_blocks",
    "all_reduce_sum",
    "reduce_scatter_sum",
    "ring_shift",
    "DATA_AXIS",
    "MODEL_AXIS",
    "data_sharding",
    "distributed_init",
    "enable_compilation_cache",
    "make_mesh",
    "pad_to_multiple",
    "replicated",
]
