"""Device-mesh parallelism utilities (the Spark-substrate replacement)."""

from .mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    data_sharding,
    distributed_init,
    enable_compilation_cache,
    make_mesh,
    pad_to_multiple,
    replicated,
)

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "data_sharding",
    "distributed_init",
    "enable_compilation_cache",
    "make_mesh",
    "pad_to_multiple",
    "replicated",
]
