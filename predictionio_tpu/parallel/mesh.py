"""Device mesh construction + sharding helpers.

The TPU-native replacement for the reference's SparkContext factory
(`/root/reference/core/src/main/scala/io/prediction/workflow/WorkflowContext.scala:25-44`):
where every reference workflow entered distribution by constructing a
SparkContext, every workflow here enters it by constructing a
`jax.sharding.Mesh` over the visible devices.  Single-chip runs get a 1-device
mesh and the same code path (XLA elides trivial collectives).

Multi-host: call :func:`distributed_init` once per process before
:func:`make_mesh`; `jax.devices()` then spans all hosts and collectives ride
ICI within a slice / DCN across slices — the NCCL/MPI-free equivalent of the
reference's Spark executor fabric (SURVEY §2.7).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "make_mesh",
    "distributed_init",
    "enable_compilation_cache",
    "force_platform",
    "data_sharding",
    "replicated",
    "shard_put",
    "pad_to_multiple",
    "DATA_AXIS",
    "MODEL_AXIS",
]


def force_platform(platform: str) -> None:
    """Force a jax platform, overriding any accelerator plugin.

    Plugins registered via sitecustomize may set the ``jax_platforms``
    CONFIG at interpreter boot, which outranks the ``JAX_PLATFORMS`` env
    var — so both must be set.  Call before any backend initializes."""
    import os

    os.environ["JAX_PLATFORMS"] = platform
    jax.config.update("jax_platforms", platform)

DATA_AXIS = "data"
MODEL_AXIS = "model"


def distributed_init(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host bring-up (`jax.distributed.initialize`); no-op when args
    are absent and the env provides no cluster spec."""
    if coordinator_address is None and num_processes is None:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def enable_compilation_cache(cache_dir: Optional[str] = None) -> None:
    """Persist XLA executables across processes.

    Training workflows recompile the same half-iteration programs every
    run; the persistent cache turns those 20-40 s TPU compiles into
    millisecond disk hits.  Default location: ``$PIO_TPU_HOME/jax_cache``.

    Hit/miss/request counts surface as
    ``pio_compile_cache_events_total{kind}`` (pio-xray hooks
    ``jax.monitoring``), so a deploy's cold-start vs warm-start is
    readable straight off ``/metrics``.
    """
    import os

    from ..obs import xray

    if cache_dir is None:
        home = os.environ.get("PIO_TPU_HOME") or os.path.expanduser(
            "~/.predictionio_tpu"
        )
        cache_dir = os.path.join(home, "jax_cache")
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    # the listeners must exist BEFORE the first compile books a cache
    # event, or cold-start counts undercount
    xray.note_compilation_cache(cache_dir)


def _visible_devices():
    """jax.devices() with CPU fallback: when the accelerator cannot
    initialize (e.g. the single TPU chip is held by another process), ops
    workflows still run on host instead of crashing.

    ``PIO_TPU_PLATFORM=cpu`` forces a platform via jax's config knob —
    needed because accelerator plugins may set ``jax_platforms`` directly
    at interpreter boot, which outranks the ``JAX_PLATFORMS`` env var."""
    import os

    forced = os.environ.get("PIO_TPU_PLATFORM")
    if forced:
        jax.config.update("jax_platforms", forced)
    else:
        env = os.environ.get("JAX_PLATFORMS")
        if env and str(jax.config.jax_platforms or "") not in (env, ""):
            # accelerator plugins (sitecustomize) may set jax_platforms at
            # interpreter boot, which outranks the env var; an explicitly
            # exported JAX_PLATFORMS is the user's word — honor it
            jax.config.update("jax_platforms", env)
    try:
        return jax.devices()
    except RuntimeError as e:
        import logging

        logging.getLogger(__name__).warning(
            "accelerator backend unavailable (%s); falling back to CPU", e
        )
        jax.config.update("jax_platforms", "cpu")
        return jax.devices()


def make_mesh(
    n_devices: Optional[int] = None,
    axis_names: Sequence[str] = (DATA_AXIS,),
    shape: Optional[Sequence[int]] = None,
) -> Mesh:
    """Build a mesh over up to ``n_devices`` visible devices.

    Default: 1-D mesh named ``data`` over all devices.  ``shape`` gives an
    explicit per-axis split (product must divide the device count).
    """
    devices = _visible_devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    if shape is None:
        shape = [n] + [1] * (len(axis_names) - 1)
    if math.prod(shape) != n:
        raise ValueError(f"mesh shape {shape} != device count {n}")
    dev_array = np.array(devices).reshape(shape)
    return Mesh(dev_array, axis_names=tuple(axis_names))


def data_sharding(mesh: Mesh, ndim: int = 1, axis: str = DATA_AXIS) -> NamedSharding:
    """Shard leading dim over the data axis, replicate the rest."""
    spec = P(axis, *([None] * (ndim - 1)))
    return NamedSharding(mesh, spec)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_put(arr, mesh: Mesh, spec) -> "jax.Array":
    """``device_put`` onto a sharding that may span processes.

    Single-process meshes take the plain ``device_put`` fast path.  On a
    multi-process mesh, ``device_put`` rejects shardings with
    non-addressable devices, so each ADDRESSABLE shard is sliced from the
    host array and the global array assembled with
    ``make_array_from_single_device_arrays`` — every process must hold a
    consistent full host copy (fine for the small index vectors and
    factor inits this serves; bulk data uses per-shard construction
    directly, see ``models/als.ALSTrainer.distributed``).
    """
    sh = NamedSharding(mesh, spec)
    if all(
        d.process_index == jax.process_index() for d in mesh.devices.flat
    ):
        return jax.device_put(arr, sh)
    arr = np.asarray(arr)
    parts = [
        jax.device_put(arr[idx], d)
        for d, idx in sh.addressable_devices_indices_map(arr.shape).items()
    ]
    return jax.make_array_from_single_device_arrays(arr.shape, sh, parts)


def pad_to_multiple(n: int, m: int) -> int:
    """Smallest multiple of ``m`` >= ``n`` (static-shape padding budgets)."""
    return ((n + m - 1) // m) * m


def fence(*arrays) -> None:
    """Block until every array's producing computation has completed.

    ``jax.Array.block_until_ready`` returns immediately on some
    remote-tunnel PJRT backends (observed on the axon v5e tunnel: a 20-matmul
    chain "blocked" in 0.1 ms while the actual device_get took 22 s), which
    silently turns wall-clock timings into dispatch timings.  Fetching one
    element is a ~4-byte d2h that cannot complete before the producer does,
    so it is a reliable fence on every backend.  Use this around ANY timed
    region; cost is one host round-trip per call (not per array): the
    per-array probe elements are packed into a single tiny device array
    and fetched together.
    """
    import numpy as np

    import jax.numpy as jnp

    # index the first element of a LOCAL shard directly (lowers to a
    # 1-element slice): ravel()[:1] would dispatch a full reshape that
    # materializes a copy of the whole array in eager mode, and global
    # indexing would fail on multi-process arrays whose shard 0 lives on
    # another host — the local shard is just as good a fence
    probes = []
    for a in jax.tree_util.tree_leaves(arrays):
        if not (hasattr(a, "ndim") and getattr(a, "size", 0)):
            continue
        shards = getattr(a, "addressable_shards", None)
        if shards:
            a = shards[0].data
            if not a.size:
                continue
        probes.append(jnp.reshape(a[(0,) * a.ndim], (1,)).astype(jnp.float32))
    if probes:
        np.asarray(jnp.concatenate(probes))
