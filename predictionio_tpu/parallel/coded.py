"""Coded (parity) factor shards — straggler tolerance for sharded ALS.

The coded-ALS idea (arXiv 2105.03631) applied to this repo's ALX-style
sharded placement (arXiv 2112.02194): alongside the ``d`` row blocks of
a ``P('data', None)`` factor table, maintain one **parity block** — the
elementwise SUM of the ``d`` blocks (the real-arithmetic analogue of an
XOR parity stripe).  Any single block is then recoverable from the
other ``d-1`` plus parity::

    block_i = parity - sum_{j != i} block_j

so a half-iteration (or a ring-top-k sweep) whose ``i``-th shard is
late or dead completes from the survivors instead of stalling the whole
ring behind one slow host — the one failure mode a pod slice actually
has.  Parity ownership ROTATES per half (RAID-5 style) so the extra
write bandwidth of keeping parity fresh is spread across the mesh
rather than hammering one chip.

Two layers live here:

* **Device math** — :func:`build_parity_fn` (parity of a sharded
  table) and :func:`build_coded_gather` (all-gather with dead blocks
  reconstructed in the same program).  Both are ``shard_map`` programs:
  identical on a virtual CPU mesh (tier-1) and a TPU slice.
* **Host orchestration** — :class:`ShardHealth`: consults the
  ``dist.*`` fault-injection points (`resilience/faults.py`) and an
  optional per-hop time budget, decides which shard (if any) must be
  served from parity this half, books
  ``pio_shard_degraded_total{shard}`` / ``pio_shard_lag_seconds`` and a
  ``dist.parity_serve`` span, and remembers kills (a dead worker stays
  dead).  With no fault plan armed and no budget set, a poll is a few
  module-global loads — the happy path costs nothing.

A single parity block tolerates ONE missing shard.  Two simultaneous
holes are unrecoverable by construction; :class:`ShardHealth` raises
:class:`ParityExhausted` loudly instead of silently serving garbage.

Simulated-cluster honesty note: on the in-process fallback mesh the
parity block is materialized REPLICATED (every virtual device holds the
[M/d, R] parity) because single-host placement is moot; on a real pod
the block belongs on the rotating owner.  The degradation *semantics*
— what is reconstructed, when, and what is booked — are identical, and
that is what the tier-1 chaos suite certifies.
"""

from __future__ import annotations

import functools
import logging
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..obs import SHARD_DEGRADED_TOTAL, SHARD_LAG_SECONDS, get_tracer, tower
from ..resilience import faults
from .collectives import shard_map
from .mesh import DATA_AXIS

logger = logging.getLogger(__name__)

__all__ = [
    "ParityExhausted",
    "ShardHealth",
    "build_parity_fn",
    "build_coded_gather",
]


class ParityExhausted(RuntimeError):
    """More shards are missing than the parity code can reconstruct."""


def build_parity_fn(mesh: Mesh, axis: str = DATA_AXIS):
    """Jitted ``[d*S, R] sharded -> [S, R] replicated`` parity (block sum).

    Called once at trainer/index build and once per half-iteration to
    refresh the parity of the table that was just updated (inside the
    coded half itself, which reuses this same psum form); the standalone
    fn exists for initialization and for serving-side index builds.
    """

    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(axis, None), out_specs=P(),
    )
    def _par(shard):
        return jax.lax.psum(shard, axis)

    return jax.jit(_par)


def build_coded_gather(mesh: Mesh, axis: str = DATA_AXIS):
    """Jitted coded all-gather: assemble the FULL table with any masked
    (late/dead) block reconstructed from parity.

    ``fn(table, parity, ok_mask) -> [M, R] replicated`` where ``table``
    is ``P(axis, None)`` sharded, ``parity`` is the replicated ``[M/d,
    R]`` block sum, and ``ok_mask`` is a replicated ``[d]`` 0/1 vector
    (0 = serve this block from parity).  With all-ones the result is
    bitwise the plain all-gather (the reconstruction branch multiplies
    by zero); with one zero the missing block is ``parity - sum(alive)``
    — exact as long as parity is current with the table.
    """
    d = mesh.shape[axis]

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(axis, None), P(), P()),
        out_specs=P(),
    )
    def _gather(shard, par, ok):
        me = jax.lax.axis_index(axis)
        masked = shard * ok[me].astype(shard.dtype)
        gathered = jax.lax.all_gather(masked, axis, axis=0, tiled=True)
        alive_sum = jax.lax.psum(masked, axis)
        recon = (par - alive_sum).astype(shard.dtype)
        blocks = gathered.reshape((d,) + shard.shape)
        okb = ok.reshape((d,) + (1,) * shard.ndim).astype(shard.dtype)
        out = blocks * okb + recon[None] * (1.0 - okb)
        return out.reshape(gathered.shape)

    return jax.jit(_gather)


def _fire(point: str, max_wait: float = 0.0):
    """One ask-and-degrade fault consultation; the host waits at most
    ``max_wait`` of the injected lag (see ``faults.fired_shard``)."""
    return faults.fired_shard(point, max_wait=max_wait)


class ShardHealth:
    """Host-side shard liveness for one coded run (a train, or a
    serving index's lifetime).

    Per half-iteration / top-k call the orchestrating host calls
    :meth:`poll`, which consults the three ``dist.*`` shard points and
    answers the ``[d]`` ok-mask the coded device program consumes:

    * ``dist.worker_kill`` — the target shard is dead from now on
      (sticky across polls; a killed worker does not come back).
    * ``dist.shard_drop``  — the target shard is out for THIS poll only
      (transient loss: a torn exchange, a dropped heartbeat).
    * ``dist.shard_delay`` — the target shard is SLOW: the rule's delay
      is the simulated wait, observed by this host.  With a hop budget
      set (``hop_budget_s``, or a request :class:`Deadline`'s remaining
      budget split per hop), a wait within budget is tolerated — the
      shard answered late but in time; a wait past budget degrades the
      shard to parity.  With no budget, any fired delay degrades (the
      deterministic default the chaos suite pins).

    Every degradation books ``pio_shard_degraded_total{shard}``,
    observes the lag in ``pio_shard_lag_seconds{op}``, and records a
    ``dist.parity_serve`` span so a degraded sweep is visible in the
    same place every other anomaly is.  Parity ownership rotates per
    poll (:attr:`parity_owner`).
    """

    def __init__(self, n_shards: int, hop_budget_s: Optional[float] = None,
                 op: str = "als.half"):
        if n_shards < 2:
            raise ValueError("coded shards need a mesh of >= 2 devices")
        self.n_shards = n_shards
        self.hop_budget_s = hop_budget_s
        self.op = op
        self.killed: set[int] = set()
        self.degraded_polls = 0
        self.parity_owner = 0
        self._polls = 0

    def poll(self, deadline=None) -> np.ndarray:
        """Consult the fault points; return the ``[d]`` f32 ok-mask for
        the next coded device call (1 = shard on time, 0 = serve from
        parity).  Raises :class:`ParityExhausted` when more than one
        shard is down — a single parity block cannot cover two holes.
        """
        self._polls += 1
        self.parity_owner = (self._polls - 1) % self.n_shards
        degraded: dict[int, float] = {}

        hit = _fire("dist.worker_kill")
        if hit is not None:
            k, lag = hit
            k %= self.n_shards
            if k not in self.killed:
                logger.warning(
                    "shard %d killed (fault plan); serving from parity "
                    "for the rest of the run", k,
                )
            self.killed.add(k)
            degraded[k] = lag
        for k in self.killed:
            degraded.setdefault(k, 0.0)

        hit = _fire("dist.shard_drop")
        if hit is not None:
            s, lag = hit
            degraded[s % self.n_shards] = lag

        budget = self.hop_budget_s
        if deadline is not None:
            # a request deadline splits into per-hop budgets: every
            # shard must answer within its share of what remains
            rem = max(deadline.remaining(), 0.0)
            per_hop = rem / max(self.n_shards, 1)
            budget = per_hop if budget is None else min(budget, per_hop)
        # the host waits out a straggler only up to its hop budget:
        # lag <= budget means the shard answered late but in time;
        # past it (or with no budget at all) it is served from parity
        # WITHOUT waiting the rest of the injected delay — degrading
        # is what keeps the call inside its deadline
        hit = _fire("dist.shard_delay", max_wait=budget or 0.0)
        if hit is not None:
            s, lag = hit
            s %= self.n_shards
            if budget is None or lag > budget:
                degraded[s] = lag
            else:
                # late but within its hop budget: tolerated, but the
                # lag is still evidence worth keeping
                SHARD_LAG_SECONDS.labels(op=self.op).observe(lag)

        if len(degraded) > 1:
            raise ParityExhausted(
                f"shards {sorted(degraded)} are all missing; a single "
                "parity block reconstructs at most one — rebuild the "
                "table or widen the code before continuing"
            )

        ok = np.ones(self.n_shards, np.float32)
        for shard, shard_lag in degraded.items():
            ok[shard] = 0.0
            self.degraded_polls += 1
            SHARD_DEGRADED_TOTAL.labels(shard=str(shard)).inc()
            SHARD_LAG_SECONDS.labels(op=self.op).observe(shard_lag)
            get_tracer().record(
                "dist.parity_serve", shard_lag,
                attrs={"shard": shard, "op": self.op,
                       "sticky": shard in self.killed},
            )
            # pio-tower sink: a degradation during a tracked training
            # run lands in the run manifest (event record + next sweep
            # record), not just in process-local metrics
            tower.note_shard_event({
                "shard": shard, "lagSeconds": round(shard_lag, 6),
                "op": self.op, "sticky": shard in self.killed,
            })
        return ok

    def summary(self) -> dict:
        """Status-JSON view (the serving status block and the harness
        report both render this)."""
        return {
            "shards": self.n_shards,
            "killed": sorted(self.killed),
            "degradedPolls": self.degraded_polls,
            "parityOwner": self.parity_owner,
        }
