"""`pio`-equivalent CLI console.

Re-expression of reference `tools/console/Console.scala:128-737` +
`console/App.scala` + `console/AccessKey.scala` on argparse.  Subcommands:

  app new|list|show|delete|data-delete|channel-new|channel-delete
  accesskey new|list|delete
  template list|get
  train | deploy | undeploy | foldin | eval | eventserver | adminserver
  dashboard
  build | unregister | run | import | export | status | upgrade | version

There is no sbt: `build` validates the engine variant and registers an
EngineManifest (RegisterEngine analogue), and engine factories are Python
callables resolved by dotted path (`WorkflowUtils.getEngine` reflection
analogue, `workflow/WorkflowUtils.scala:60-77`).
"""

from __future__ import annotations

import argparse
import importlib
import json
import sys
from pathlib import Path
from typing import Any, Optional

from .. import __version__
from ..storage.metadata import AccessKey
from ..storage.registry import Storage, get_storage

__all__ = ["main", "resolve_attr", "load_engine_from_variant"]


def resolve_attr(path: str) -> Any:
    """'package.module.attr' -> attr (the reflection-loader analogue)."""
    mod_name, _, attr = path.rpartition(".")
    if not mod_name:
        raise ValueError(f"invalid dotted path: {path!r}")
    mod = importlib.import_module(mod_name)
    try:
        return getattr(mod, attr)
    except AttributeError as e:
        raise ValueError(f"{attr!r} not found in module {mod_name}") from e


def _engine_dir_on_path(variant_path: str | Path, factory_path: str) -> None:
    """Make a scaffolded engine dir importable: its ``engine.py`` is the
    factory module when engineFactory is ``engine.<attr>`` (the
    `template get` layout).  Evicts a stale ``engine`` module loaded from
    a different engine dir."""
    engine_dir = str(Path(variant_path).resolve().parent)
    top = factory_path.split(".", 1)[0]
    candidate = Path(engine_dir) / f"{top}.py"
    if not candidate.exists():
        return
    if engine_dir not in sys.path:
        sys.path.insert(0, engine_dir)
    mod = sys.modules.get(top)
    if mod is not None and getattr(mod, "__file__", None) != str(candidate):
        del sys.modules[top]


def load_engine_from_variant(
    variant_path: str | Path,
    engine_factory: Optional[str] = None,
    return_factory: bool = False,
):
    """engine.json -> (engine, engine_params, variant dict).

    Two dispatch forms: ``engineFactory`` (dotted path, the classic
    reflection-loader analogue) or ``engine`` (a pio-forge registry
    name — the engine.json of a one-file engine is just
    ``{"engine": "myengine"}`` plus optional component overrides; the
    spec's default params fill the gaps).  ``return_factory=True``
    appends the factory object (an EngineFactory instance, or the bare
    callable) so callers needing factory-level API like
    ``engine_params(key)`` don't re-resolve/instantiate it."""
    variant = json.loads(Path(variant_path).read_text())
    factory_path = engine_factory or variant.get("engineFactory")
    if not factory_path:
        name = variant.get("engine")
        if name:
            from .. import engines

            try:
                spec = engines.get_engine_spec(name)
            except KeyError:
                # an engine.json inside a not-yet-discovered engine dir:
                # load THAT dir (the --engine-json form must work
                # without PIO_TPU_ENGINE_PATH)
                engines.discovery.load_engine_dir(
                    Path(variant_path).resolve().parent
                )
                spec = engines.get_engine_spec(name)
            merged = spec.default_variant()
            merged.update(variant)
            engine = spec.build()
            out = (engine, engine.params_from_variant(merged), merged)
            return (*out, spec.factory) if return_factory else out
        raise ValueError(
            "engine.json must declare 'engineFactory' or 'engine' "
            "(or pass --engine-factory)"
        )
    _engine_dir_on_path(variant_path, factory_path)
    factory = resolve_attr(factory_path)
    obj = factory() if isinstance(factory, type) else factory
    if not hasattr(obj, "apply") and callable(obj):
        obj = obj()  # plain function factory -> Engine (or EngineFactory)
    if hasattr(obj, "apply"):  # EngineFactory object
        engine = obj.apply()
        factory_obj = obj
    else:
        engine = obj
        factory_obj = factory
    out = (engine, engine.params_from_variant(variant), variant)
    return (*out, factory_obj) if return_factory else out


def _out(msg: str) -> None:
    print(msg)


def _apply_obs_flags(args) -> None:
    """Wire the pio-obs/pio-xray knobs shared by the server/workflow
    commands: ``--telemetry-dir`` (span JSONL journal location),
    ``--no-metrics`` (404 the /metrics + /debug/xray mounts),
    ``--xray-sample-s`` (device sampler cadence) and
    ``--flight-capacity`` (slow-query flight recorder depth)."""
    from ..obs import configure, get_flight_recorder, xray

    configure(
        journal_dir=getattr(args, "telemetry_dir", None),
        metrics=(False if getattr(args, "no_metrics", False) else None),
    )
    sample_s = getattr(args, "xray_sample_s", None)
    if sample_s is not None:
        xray.set_sample_period(sample_s)
    flight_n = getattr(args, "flight_capacity", None)
    if flight_n is not None:
        get_flight_recorder().set_capacity(flight_n)
    if getattr(args, "no_profiler", False):
        # pio-scope opt-out: the servers' ensure_started() becomes a
        # no-op; the TimedLock contention lens keeps booking (its cost
        # is per-contended-acquire, not per-sample)
        from ..obs import scope

        scope.set_enabled(False)


def _add_obs_args(p) -> None:
    p.add_argument("--telemetry-dir", metavar="DIR",
                   help="journal pio-obs spans as JSON lines to "
                   "DIR/spans-<pid>.jsonl (size-capped rotated "
                   "segments; default: in-memory ring only; "
                   "PIO_TPU_TELEMETRY=1 journals under "
                   "$PIO_TPU_HOME/telemetry)")
    p.add_argument("--no-metrics", action="store_true",
                   help="disable the GET /metrics Prometheus "
                   "exposition and GET /debug/xray (recording still "
                   "happens; only the endpoints answer 404)")
    p.add_argument("--xray-sample-s", type=float, default=None,
                   metavar="SEC",
                   help="pio-xray device-memory sampler period "
                   "(default: $PIO_TPU_XRAY_SAMPLE_S or 10; <= 0 "
                   "disables the sampler)")
    p.add_argument("--no-profiler", action="store_true",
                   help="disable the pio-scope always-on sampling "
                   "profiler (GET /debug/pprof then answers an empty "
                   "profile; the lock-contention lens stays on; "
                   "PIO_TPU_SCOPE=0 is the env equivalent)")


# --------------------------------------------------------------------------
# app / accesskey ops (console/App.scala:34-498, console/AccessKey.scala)
# --------------------------------------------------------------------------


def _resolve_channel(md, app_id: int, name: str):
    """Channel name -> Channel for an app, or None if absent."""
    for c in md.channel_get_by_app(app_id):
        if c.name == name:
            return c
    return None


def cmd_app(args, storage: Storage) -> int:
    md = storage.get_metadata()
    es = storage.get_event_store()
    if args.app_command == "new":
        if md.app_get_by_name(args.name):
            _out(f"Error: app '{args.name}' already exists.")
            return 1
        app = md.app_insert(args.name, args.description)
        es.init_channel(app.id)
        key = md.access_key_insert(
            AccessKey(key=args.access_key or "", appid=app.id)
        )
        _out(f"Created app '{app.name}' (id {app.id}).")
        _out(f"Access key: {key}")
        return 0
    if args.app_command == "list":
        for app in md.app_get_all():
            keys = md.access_key_get_by_app(app.id)
            _out(f"{app.id:>6}  {app.name}  keys={len(keys)}")
        return 0
    if args.app_command == "show":
        app = md.app_get_by_name(args.name)
        if app is None:
            _out(f"Error: app '{args.name}' not found.")
            return 1
        _out(f"App: {app.name} (id {app.id})")
        _out(f"Description: {app.description or ''}")
        for k in md.access_key_get_by_app(app.id):
            events = ",".join(k.events) if k.events else "(all)"
            _out(f"Access key: {k.key} events={events}")
        for c in md.channel_get_by_app(app.id):
            _out(f"Channel: {c.name} (id {c.id})")
        return 0
    if args.app_command == "delete":
        app = md.app_get_by_name(args.name)
        if app is None:
            _out(f"Error: app '{args.name}' not found.")
            return 1
        for c in md.channel_get_by_app(app.id):
            es.remove_channel(app.id, c.id)
            md.channel_delete(c.id)
        es.remove_channel(app.id)
        for k in md.access_key_get_by_app(app.id):
            md.access_key_delete(k.key)
        md.app_delete(app.id)
        _out(f"Deleted app '{args.name}'.")
        return 0
    if args.app_command == "data-delete":
        app = md.app_get_by_name(args.name)
        if app is None:
            _out(f"Error: app '{args.name}' not found.")
            return 1
        if args.channel:
            chan = _resolve_channel(md, app.id, args.channel)
            if chan is None:
                _out(f"Error: channel '{args.channel}' not found.")
                return 1
            es.remove_channel(app.id, chan.id)
            es.init_channel(app.id, chan.id)
        else:
            es.remove_channel(app.id)
            es.init_channel(app.id)
        _out(f"Deleted event data of app '{args.name}'.")
        return 0
    if args.app_command == "trim":
        from ..storage.event import parse_time
        from ..tools.trim import trim_events

        app = md.app_get_by_name(args.name)
        if app is None:
            _out(f"Error: app '{args.name}' not found.")
            return 1
        channel_id = 0
        if args.channel:
            chan = _resolve_channel(md, app.id, args.channel)
            if chan is None:
                _out(f"Error: channel '{args.channel}' not found.")
                return 1
            channel_id = chan.id
        try:
            before = parse_time(args.before) if args.before else None
        except ValueError as e:
            _out(f"Error: invalid --before time: {e}")
            return 1
        try:
            n = trim_events(
                es, app.id, channel_id,
                before=before,
                event_names=args.event or None,
                keep_special=not args.all,
            )
        except ValueError as e:
            _out(f"Error: {e}")
            return 1
        _out(f"Trimmed {n} events from app '{args.name}'.")
        if args.compact:
            es.compact()
            _out("Compacted the event store (space reclaimed).")
        return 0
    if args.app_command == "compact":
        es.compact()
        _out("Compacted the event store (space reclaimed).")
        return 0
    if args.app_command == "channel-new":
        app = md.app_get_by_name(args.name)
        if app is None:
            _out(f"Error: app '{args.name}' not found.")
            return 1
        try:
            c = md.channel_insert(args.channel, app.id)
        except ValueError as e:
            _out(f"Error: {e}")
            return 1
        es.init_channel(app.id, c.id)
        _out(f"Created channel '{c.name}' (id {c.id}).")
        return 0
    if args.app_command == "channel-delete":
        app = md.app_get_by_name(args.name)
        if app is None:
            _out(f"Error: app '{args.name}' not found.")
            return 1
        chan = _resolve_channel(md, app.id, args.channel)
        if chan is None:
            _out(f"Error: channel '{args.channel}' not found.")
            return 1
        es.remove_channel(app.id, chan.id)
        md.channel_delete(chan.id)
        _out(f"Deleted channel '{args.channel}'.")
        return 0
    raise AssertionError(args.app_command)


def cmd_accesskey(args, storage: Storage) -> int:
    md = storage.get_metadata()
    if args.ak_command == "new":
        app = md.app_get_by_name(args.app_name)
        if app is None:
            _out(f"Error: app '{args.app_name}' not found.")
            return 1
        key = md.access_key_insert(
            AccessKey(key="", appid=app.id, events=args.events or [])
        )
        _out(f"Access key: {key}")
        return 0
    if args.ak_command == "list":
        keys = md.access_key_get_all()
        if args.app_name:
            app = md.app_get_by_name(args.app_name)
            if app is None:
                _out(f"Error: app '{args.app_name}' not found.")
                return 1
            keys = [k for k in keys if k.appid == app.id]
        for k in keys:
            events = ",".join(k.events) if k.events else "(all)"
            _out(f"{k.key}  appid={k.appid}  events={events}")
        return 0
    if args.ak_command == "delete":
        md.access_key_delete(args.key)
        _out(f"Deleted access key {args.key}.")
        return 0
    raise AssertionError(args.ak_command)


# --------------------------------------------------------------------------
# train / deploy / eval / servers
# --------------------------------------------------------------------------


def _load_engine_for_args(args, return_factory: bool = False):
    """ONE resolution path for every workflow command: ``--engine NAME``
    (pio-forge registry dispatch — no engine.json file needed) or
    ``--engine-json PATH``.  Returns ``(engine, ep, variant,
    variant_key[, factory])`` where ``variant_key`` is the
    engine-variant string instances are registered/looked up under."""
    from ..tools.template_gallery import verify_template_min_version

    name = getattr(args, "engine", None)
    if name:
        from .. import engines

        spec = engines.get_engine_spec(name)
        engine, ep, variant = engines.resolve(name)
        out = (engine, ep, variant, spec.instance_variant_key())
        return (*out, spec.factory) if return_factory else out
    verify_template_min_version(Path(args.engine_json).parent)
    loaded = load_engine_from_variant(
        args.engine_json, args.engine_factory, return_factory=return_factory
    )
    out = (*loaded[:3], str(args.engine_json))
    return (*out, loaded[3]) if return_factory else out


def _resolve_instance_id(md, engine_id: str, variant_key: str,
                         explicit: Optional[str]):
    """The deploy/foldin glue, deduplicated: an explicit instance id is
    verified, else the latest COMPLETED instance for (engine_id,
    variant_key) wins.  Returns ``(iid, error_message)``."""
    if explicit:
        if md.engine_instance_get(explicit) is None:
            return None, f"engine instance '{explicit}' not found."
        return explicit, None
    latest = md.engine_instance_get_latest_completed(
        engine_id, "1", variant_key
    )
    if latest is None:
        return None, ("no completed engine instance found; "
                      "run train first.")
    return latest.id, None


def cmd_engines(args, storage: Storage) -> int:
    """pio-forge registry view: every engine one registration away from
    `train/deploy/eval --engine NAME` — built-ins plus anything on
    PIO_TPU_ENGINE_PATH."""
    from .. import engines

    if args.engines_command == "list":
        specs = engines.list_engine_specs()
        for spec in specs:
            src = "" if spec.source == "builtin" else f"  [{spec.source}]"
            _out(f"{spec.name:<26} {spec.description}{src}")
        _out(f"({len(specs)} engines registered)")
        return 0
    if args.engines_command == "describe":
        try:
            spec = engines.get_engine_spec(args.name)
        except KeyError as e:
            _out(f"Error: {e.args[0]}")
            return 1
        _out(json.dumps(spec.describe(), indent=2))
        return 0
    raise AssertionError(args.engines_command)


def cmd_train(args, storage: Storage) -> int:
    from ..controller.base import WorkflowContext
    from ..parallel.mesh import enable_compilation_cache
    from ..workflow.params import WorkflowParams
    from ..workflow.train import run_train

    enable_compilation_cache()
    if getattr(args, "scan_cache", False):
        import os

        os.environ["PIO_TPU_SCAN_CACHE"] = "1"
    if args.coordinator or args.num_processes is not None:
        # multi-host bring-up: each host runs the same `pio-tpu train`
        # with its own --process-id; collectives then span hosts
        from ..parallel.mesh import distributed_init

        distributed_init(
            coordinator_address=args.coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id,
        )
    engine, ep, variant, variant_key, factory = _load_engine_for_args(
        args, return_factory=True
    )
    if args.engine_params_key:
        # programmatic params override: EngineFactory.engine_params(key)
        # (reference CreateWorkflow --engine-params-key)
        if not hasattr(factory, "engine_params"):
            _out("Error: --engine-params-key needs an EngineFactory with "
                 "engine_params(key).")
            return 1
        try:
            ep = factory.engine_params(args.engine_params_key)
        except KeyError as e:
            _out(f"Error: unknown engine params key: {e}")
            return 1
    ctx = WorkflowContext(storage=storage, mode="Training", batch=args.batch)
    wp = WorkflowParams(
        batch=args.batch,
        skip_sanity_check=args.skip_sanity_check,
        stop_after_read=args.stop_after_read,
        stop_after_prepare=args.stop_after_prepare,
    )
    iid = run_train(
        engine, ep, ctx=ctx, workflow_params=wp,
        engine_id=variant.get("id", "default"),
        engine_variant=variant_key,
        engine_factory=args.engine_factory or variant.get("engineFactory", ""),
    )
    _out(f"Training completed. Engine instance id: {iid}")
    return 0


def cmd_deploy(args, storage: Storage) -> int:
    from ..controller.base import WorkflowContext
    from ..parallel.mesh import enable_compilation_cache
    from ..server.serving import EngineServer, ServerConfig

    if getattr(args, "replicas", 0) and args.replicas > 1:
        # pio-surge fleet mode: N replica processes + one router
        return _deploy_fleet(args)
    enable_compilation_cache()
    if getattr(args, "scan_cache", False):
        import os

        os.environ["PIO_TPU_SCAN_CACHE"] = "1"
    # pio-hive: `deploy --multi tenants.json` boots ONE server hosting
    # every tenant in the manifest.  Tenant 0 is the anchor (loaded
    # eagerly as the server's own components, pinned); the rest load
    # lazily on first query under the registry's memory budget.
    tenants = None
    if getattr(args, "multi", None):
        tenants = _build_tenant_registry(args, storage)
        anchor = tenants.spec(tenants.anchor_key)
        if anchor.engine_name:
            args.engine = anchor.engine_name
        else:
            args.engine_json = anchor.engine_json
        if anchor.instance_id and not args.engine_instance_id:
            args.engine_instance_id = anchor.instance_id
    engine, ep, variant, variant_key = _load_engine_for_args(args)
    md = storage.get_metadata()
    engine_id = variant.get("id", "default")
    iid, err = _resolve_instance_id(
        md, engine_id, variant_key, args.engine_instance_id
    )
    if err:
        _out(f"Error: {err}")
        return 1
    ctx = WorkflowContext(storage=storage, mode="Serving")
    server = EngineServer(
        engine, ep, iid, ctx=ctx,
        config=ServerConfig(
            host=args.ip, port=args.port,
            feedback=args.feedback,
            event_server_url=args.event_server_url,
            access_key=args.accesskey,
            log_url=args.log_url,
            log_prefix=args.log_prefix,
            microbatch=args.microbatch,
            shared_batcher=(args.shared_batcher != "off"),
            query_timeout_s=args.query_timeout,
            feedback_capacity=args.feedback_capacity,
            breaker_failures=args.breaker_failures,
            breaker_reset_s=args.breaker_reset,
            foldin_poll_s=args.foldin_poll,
            edge=args.edge,
            max_connections=args.max_connections,
            slo_ms=getattr(args, "slo_ms", None),
        ),
        engine_id=engine_id,
        engine_variant=variant_key,
        tenants=tenants,
    )
    # undeploy a stale server holding the port (CreateServer.scala:266-288)
    import urllib.error
    import urllib.request

    stale_host = "127.0.0.1" if args.ip == "0.0.0.0" else args.ip
    try:
        with urllib.request.urlopen(
            urllib.request.Request(
                f"http://{stale_host}:{args.port}/stop", method="POST"
            ),
            timeout=2,
        ):
            _out(f"Undeployed stale engine server on port {args.port}.")
            import time

            time.sleep(0.5)
    except (urllib.error.URLError, OSError):
        pass
    if args.port_file:
        # bind now so the announced port is real (--port 0 = ephemeral);
        # the replica spawner (deploy --replicas) reads this file
        server._bind()
        pf = Path(args.port_file)
        pf.parent.mkdir(parents=True, exist_ok=True)
        pf.write_text(f"{server.port}\n")
    _out(f"Deploying engine instance {iid} on {args.ip}:{server.port}")
    server.serve_forever()
    return 0


def _build_tenant_registry(args, storage):
    """Parse ``--multi`` tenants.json into a TenantRegistry, resolving
    each tenant's app id + access key from metadata (attribution and
    accessKey-routing need them; absent apps just lose conversion
    scanning, loudly)."""
    from ..tenancy import TenantRegistry, load_tenant_manifest

    specs, opts = load_tenant_manifest(args.multi)
    for spec in specs:
        if spec.engine_json is None and spec.engine_name is None:
            _out(f"Error: tenant {spec.key_str} has no engineJson or "
                 "engine name.")
            raise SystemExit(1)
    if getattr(args, "memory_budget", None) is not None:
        opts["memory_budget_bytes"] = args.memory_budget
    # pio-pilot: `--autopilot` wins over the manifest's "autopilot"
    # block; "on"/"1" enables with defaults, anything else is a JSON
    # knob dict (alpha/beta/minLift/minSamples/maxStep/minWeight/...)
    ap = getattr(args, "autopilot", None)
    if ap:
        if ap.strip().lower() in ("1", "on", "true"):
            opts["autopilot"] = {}
        else:
            try:
                opts["autopilot"] = json.loads(ap)
            except json.JSONDecodeError as e:
                _out(f"Error: --autopilot is neither 'on' nor valid "
                     f"JSON: {e}")
                raise SystemExit(1)
    md = storage.get_metadata()
    for spec in specs:
        app = md.app_get_by_name(spec.app)
        if app is None:
            _out(f"Warning: tenant app '{spec.app}' not found in "
                 "metadata; accessKey routing and online-eval "
                 "conversion scanning are off for it.")
            continue
        spec.app_id = app.id
        if spec.access_key is None:
            keys = md.access_key_get_by_app(app.id)
            if keys:
                spec.access_key = keys[0].key
    return TenantRegistry(specs, **opts)


def _deploy_fleet(args) -> int:
    """``deploy --replicas N``: spawn N single-replica deploy
    subprocesses on ephemeral ports, then run the router in THIS
    process on the requested port.  Ctrl-C / POST /stop tears the
    whole fleet down."""
    import atexit
    import tempfile

    from ..server.router import (
        Replica, ReplicaSupervisor, RouterConfig, RouterServer,
        spawn_replica, wait_for_port_file,
    )

    coord_dir = Path(tempfile.mkdtemp(prefix="pio-surge-fleet-"))
    extra = []
    for flag, val in (
        ("--engine-factory", args.engine_factory),
        ("--engine-instance-id", args.engine_instance_id),
        ("--microbatch", args.microbatch),
        ("--shared-batcher", args.shared_batcher),
        ("--edge", args.edge),
        # pio-hive: every replica hosts the same tenant manifest, so
        # the fleet multiplexes N tenants x N replicas
        ("--multi", getattr(args, "multi", None)),
    ):
        if val:
            extra += [flag, str(val)]
    for flag, val in (
        ("--query-timeout", args.query_timeout),
        ("--foldin-poll", args.foldin_poll),
        ("--max-connections", args.max_connections),
        ("--memory-budget", getattr(args, "memory_budget", None)),
        # pio-lens: every replica arms its own burn-rate gauges too —
        # the router's merged /metrics then shows them per replica
        ("--slo-ms", getattr(args, "slo_ms", None)),
    ):
        if val is not None:
            extra += [flag, str(val)]
    if getattr(args, "scan_cache", False):
        extra.append("--scan-cache")
    if getattr(args, "no_profiler", False):
        extra.append("--no-profiler")
    def spawner(i):
        return spawn_replica(args.engine_json, i, coord_dir,
                             extra_args=extra,
                             engine_name=getattr(args, "engine", None))

    spawned = [spawner(i) for i in range(args.replicas)]
    supervisor = (
        ReplicaSupervisor(spawner)
        if not getattr(args, "no_respawn", False) else None
    )

    def reap():
        # the supervisor may have replaced boot-time processes with
        # respawns — reap whatever is CURRENTLY tracked, plus the boot
        # list (dead originals reap as no-ops)
        procs = [s["proc"] for s in spawned]
        if supervisor is not None:
            procs += supervisor.live_procs()
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:
                p.kill()

    atexit.register(reap)
    replicas = []
    for s in spawned:
        port = wait_for_port_file(s)
        _out(f"Replica {s['index']} up on 127.0.0.1:{port} "
             f"(log: {s['log_path']})")
        replica = Replica(
            f"replica-{s['index']}", "127.0.0.1", port,
            breaker_failures=args.breaker_failures,
        )
        if supervisor is not None:
            supervisor.attach(replica, s)
        replicas.append(replica)
    router = RouterServer(replicas, RouterConfig(
        host=args.ip, port=args.port,
        health_interval_s=args.health_interval,
        max_connections=args.max_connections,
        push_foldin_s=args.push_foldin,
        slo_ms=getattr(args, "slo_ms", None),
    ), supervisor=supervisor)
    if args.port_file:
        router._bind()
        pf = Path(args.port_file)
        pf.parent.mkdir(parents=True, exist_ok=True)
        pf.write_text(f"{router.port}\n")
    _out(f"Router fronting {len(replicas)} replicas on "
         f"{args.ip}:{args.port}")
    try:
        router.serve_forever()
    finally:
        reap()
    return 0


def cmd_foldin(args, storage: Storage) -> int:
    """pio-live: incremental ALS fold-in (one-shot or --watch daemon).

    Scans the event store past the per-(app, channel) watermark, solves
    the touched/new factor rows against the frozen opposite table, and
    publishes delta links that a deployed engine server
    (``deploy --foldin-poll``) patches in live — fresh events become
    fresh predictions without ``pio train`` or ``/reload``."""
    from ..controller.base import WorkflowContext
    from ..live import FoldInRunner
    from ..parallel.mesh import enable_compilation_cache

    enable_compilation_cache()
    engine, ep, variant, variant_key = _load_engine_for_args(args)
    md = storage.get_metadata()
    engine_id = variant.get("id", "default")
    iid, err = _resolve_instance_id(
        md, engine_id, variant_key, args.engine_instance_id
    )
    if err:
        _out(f"Error: {err}")
        return 1
    ctx = WorkflowContext(storage=storage, mode="Serving")
    try:
        runner = FoldInRunner(
            storage, engine, ep, iid, channel_id=args.channel, ctx=ctx,
            from_now=args.from_now,
        )
    except ValueError as e:
        _out(f"Error: {e}")
        return 1
    _out(f"Fold-in on instance {iid} (app {runner.app_id}, "
         f"watermark rowid {runner.cursor}, chain seq {runner.seq})")
    if args.watch:
        _out(f"Watching for events every {args.interval}s "
             "(Ctrl-C to stop)...")
        try:
            runner.watch(
                interval_s=args.interval,
                max_cycles=args.max_cycles,
                on_cycle=lambda s: _out(json.dumps(s)),
            )
        except KeyboardInterrupt:
            _out("Stopped.")
        return 0
    stats = runner.cycle()
    if stats is None:
        _out(f"No new events past watermark rowid {runner.cursor}; "
             "nothing to fold in.")
    else:
        _out(json.dumps(stats))
    return 0


def cmd_eval(args, storage: Storage) -> int:
    from ..controller.base import WorkflowContext
    from ..parallel.mesh import enable_compilation_cache
    from ..workflow.evaluate import run_evaluation

    enable_compilation_cache()
    if getattr(args, "scan_cache", False):
        import os

        os.environ["PIO_TPU_SCAN_CACHE"] = "1"
    if getattr(args, "engine", None):
        # pio-forge: `eval --engine NAME` dispatches the spec's
        # declared evaluation — no dotted path to remember
        from .. import engines

        spec = engines.get_engine_spec(args.engine)
        if spec.evaluation is None:
            _out(f"Error: engine '{spec.name}' declares no evaluation; "
                 "pass a dotted evaluation path instead.")
            return 1
        evaluation = spec.evaluation
    elif args.evaluation:
        evaluation = resolve_attr(args.evaluation)
    else:
        _out("Error: pass an evaluation dotted path or --engine NAME.")
        return 1
    if callable(evaluation) and not hasattr(evaluation, "engine"):
        evaluation = evaluation()
    params_list = None
    if args.engine_params_generator:
        gen = resolve_attr(args.engine_params_generator)
        if callable(gen) and not hasattr(gen, "engine_params_list"):
            gen = gen()
        params_list = list(gen.engine_params_list)
    ctx = WorkflowContext(storage=storage, mode="Evaluation", batch=args.batch)
    eval_class = args.evaluation
    if not eval_class and getattr(args, "engine", None):
        from .. import engines

        eval_class = engines.get_engine_spec(args.engine).evaluation_path
    eval_id, result = run_evaluation(
        evaluation, params_list, ctx=ctx,
        evaluation_class=eval_class or "",
        engine_params_generator_class=args.engine_params_generator or "",
        parallelism=args.parallelism,
    )
    _out(result.to_one_liner())
    _out(f"Evaluation completed. Instance id: {eval_id}")
    return 0


def cmd_eventserver(args, storage: Storage) -> int:
    if getattr(args, "workers", 0) and args.workers > 1:
        return _eventserver_fleet(args, storage)
    from ..server.event_server import EventServer, EventServerConfig

    owned = None
    if getattr(args, "owned_shards", None):
        owned = [int(s) for s in args.owned_shards.split(",") if s != ""]
    elif getattr(args, "worker_index", None) is not None:
        # shard-owner worker (pio-levee): stripe ownership by index
        from ..server.ingest_router import shards_for_worker

        es = storage.get_event_store()
        owned = shards_for_worker(
            args.worker_index, args.worker_count,
            getattr(es, "n_shards", 1),
        )
    server = EventServer(
        storage, EventServerConfig(
            host=args.ip, port=args.port,
            stats=args.stats,
            write_retries=args.write_retries,
            write_backoff_s=args.write_backoff,
            max_connections=args.max_connections,
            wal_dir=getattr(args, "wal_dir", None),
            wal_fsync=not getattr(args, "no_wal_fsync", False),
            owned_shards=owned,
            ttl_s=getattr(args, "ttl", None),
            compact_interval_s=getattr(args, "compact_interval", None),
            slo_ms=getattr(args, "slo_ms", None),
        )
    )
    if getattr(args, "port_file", None):
        # bind first so the announced port is real (--port 0 =
        # ephemeral); the ingest-router spawner reads this file
        server._bind()
        pf = Path(args.port_file)
        pf.parent.mkdir(parents=True, exist_ok=True)
        pf.write_text(f"{server.port}\n")
    role = f" (shard owner: {owned})" if owned is not None else ""
    _out(f"Event server running on {args.ip}:{server.port}{role}")
    server.serve_forever()
    return 0


def _eventserver_fleet(args, storage: Storage) -> int:
    """pio-levee: ``eventserver --workers N`` — spawn N shard-owner
    worker processes (each owning ``shard % N == index`` of the sharded
    store, each with its own ingest WAL) and run the ingest router in
    THIS process on the requested port."""
    import atexit
    import tempfile

    from ..server.ingest_router import (
        IngestRouterConfig, boot_ingest_fleet,
    )

    es = storage.get_event_store()
    n_shards = getattr(es, "n_shards", 1)
    if args.workers > n_shards:
        _out(f"error: --workers {args.workers} exceeds the store's "
             f"{n_shards} shards; extra workers would own nothing")
        return 1
    coord_dir = Path(tempfile.mkdtemp(prefix="pio-levee-fleet-"))
    wal_root = Path(args.wal_dir) if getattr(args, "wal_dir", None) \
        else coord_dir / "wal"
    extra = []
    for flag, val in (
        ("--write-retries", args.write_retries),
        ("--write-backoff", args.write_backoff),
        ("--max-connections", args.max_connections),
        ("--ttl", getattr(args, "ttl", None)),
        ("--compact-interval", getattr(args, "compact_interval", None)),
        # each worker arms its own write-SLO burn gauges; the router's
        # merged /metrics shows them per worker
        ("--slo-ms", getattr(args, "slo_ms", None)),
    ):
        if val is not None:
            extra += [flag, str(val)]
    if getattr(args, "no_wal_fsync", False):
        extra.append("--no-wal-fsync")
    if getattr(args, "no_profiler", False):
        extra.append("--no-profiler")
    router, spawned = boot_ingest_fleet(
        args.workers, n_shards, coord_dir,
        config=IngestRouterConfig(
            host=args.ip, port=args.port,
            max_connections=args.max_connections,
        ),
        wal_root=wal_root, extra_args=extra,
        respawn=not getattr(args, "no_respawn", False),
    )
    for w, s in zip(router.workers, spawned):
        _out(f"Ingest worker {w.index} up on 127.0.0.1:{w.port} "
             f"owning shards {w.shards} (log: {s['log_path']})")

    def reap():
        procs = [s["proc"] for s in spawned]
        if router.supervisor is not None:
            procs += router.supervisor.live_procs()
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:
                p.kill()

    atexit.register(reap)
    if getattr(args, "port_file", None):
        router._bind()
        pf = Path(args.port_file)
        pf.parent.mkdir(parents=True, exist_ok=True)
        pf.write_text(f"{router.port}\n")
    _out(f"Ingest router fronting {args.workers} shard-owner workers "
         f"({n_shards} shards) on {args.ip}:{args.port}")
    try:
        router.serve_forever()
    finally:
        reap()
    return 0


def cmd_adminserver(args, storage: Storage) -> int:
    from ..server.admin import AdminServer

    server = AdminServer(storage, host=args.ip, port=args.port)
    _out(f"Admin server running on {args.ip}:{args.port}")
    server.serve_forever()
    return 0


def cmd_dashboard(args, storage: Storage) -> int:
    from ..server.dashboard import DashboardServer

    server = DashboardServer(storage, host=args.ip, port=args.port)
    _out(f"Dashboard running on {args.ip}:{args.port}")
    server.serve_forever()
    return 0


def cmd_import(args, storage: Storage) -> int:
    from ..tools.import_export import import_events

    es = storage.get_event_store()
    es.init_channel(args.appid, args.channel)
    # import_events infers the format (extension or content magic) and
    # routes to the JSON-lines / columnar / parquet reader itself
    n = import_events(args.input, es, args.appid, args.channel)
    _out(f"Imported {n} events.")
    return 0


def cmd_export(args, storage: Storage) -> int:
    from ..tools.import_export import columnar_path, export_events

    es = storage.get_event_store()
    es.init_channel(args.appid, args.channel)
    n = export_events(args.output, es, args.appid, args.channel,
                      fmt=args.format)
    from ..tools.import_export import infer_format

    fmt = args.format or infer_format(args.output)
    written = columnar_path(args.output) if fmt == "columnar" else args.output
    _out(f"Exported {n} events to {written}.")
    return 0


def cmd_template(args, storage: Storage) -> int:
    """Offline gallery (`console/Template.scala:130-427` analogue)."""
    import http.client
    import urllib.error

    from ..tools.template_gallery import (
        TemplateVersionError, fetch_index, list_templates, scaffold,
        scaffold_from_archive, scaffold_from_index, scaffold_from_url,
    )

    if args.template_command == "list":
        if args.index_url:
            # remote gallery browse (Template.scala:130-170 analogue)
            try:
                entries = fetch_index(args.index_url)
            except (ValueError, urllib.error.URLError, OSError,
                    http.client.HTTPException) as e:
                _out(f"Error: {e}")
                return 1
            for e in entries:
                _out(f"{e['name']:<26} {e.get('description', '')}")
            return 0
        for t in list_templates():
            _out(f"{t.name:<26} {t.description}")
        return 0
    if args.template_command == "get":
        try:
            if args.from_archive:
                target = scaffold_from_archive(
                    args.from_archive, args.directory or args.name
                )
            elif args.from_url:
                target = scaffold_from_url(
                    args.from_url, args.directory or args.name
                )
            elif args.index_url:
                target = scaffold_from_index(
                    args.name, args.directory or args.name, args.index_url
                )
            else:
                target = scaffold(args.name, args.directory or args.name)
        except (KeyError, FileExistsError, FileNotFoundError, ValueError,
                TemplateVersionError, urllib.error.URLError, OSError,
                http.client.HTTPException) as e:
            # HTTPException covers truncated/garbage responses
            # (IncompleteRead, BadStatusLine) that are not OSErrors
            _out(f"Error: {e}")
            return 1
        _out(f"Engine template '{args.name}' created at {target}/")
        return 0
    raise AssertionError(args.template_command)


def cmd_build(args, storage: Storage) -> int:
    """Validate the engine variant and register its manifest.

    The reference `build` runs sbt then `RegisterEngine` (Console.scala:
    772-802); with Python engines the build step reduces to import-checking
    the factory and upserting the `EngineManifest`.
    """
    from ..storage.metadata import EngineManifest
    from ..tools.template_gallery import verify_template_min_version

    verify_template_min_version(Path(args.engine_json).parent)
    try:
        engine, ep, variant = load_engine_from_variant(
            args.engine_json, args.engine_factory
        )
    except Exception as e:
        _out(f"Error: engine variant failed to load: {e}")
        return 1
    engine_id = variant.get("id", Path(args.engine_json).resolve().parent.name)
    storage.get_metadata().manifest_upsert(
        EngineManifest(
            id=engine_id,
            version=args.engine_version,
            name=engine_id,
            description=variant.get("description"),
            files=[str(Path(args.engine_json).resolve())],
            engine_factory=args.engine_factory
            or variant.get("engineFactory", ""),
        )
    )
    _out(f"Engine '{engine_id}' built and registered "
         f"(version {args.engine_version}).")
    return 0


def cmd_unregister(args, storage: Storage) -> int:
    variant = json.loads(Path(args.engine_json).read_text())
    engine_id = variant.get("id", Path(args.engine_json).resolve().parent.name)
    storage.get_metadata().manifest_delete(engine_id, args.engine_version)
    _out(f"Engine '{engine_id}' unregistered.")
    return 0


def cmd_run(args, storage: Storage) -> int:
    """Run an arbitrary dotted-path main under the framework env
    (Console `run` analogue — there it spark-submits a user class)."""
    fn = resolve_attr(args.main_class)
    if not callable(fn):
        _out(f"Error: {args.main_class} resolved to a non-callable "
             f"{type(fn).__name__}.")
        return 1
    rv = fn(*args.args)
    return int(rv) if isinstance(rv, int) else 0


def cmd_undeploy(args, storage: Storage) -> int:
    """POST /stop to a deployed engine server (Console.scala undeploy)."""
    import urllib.error
    import urllib.request

    url = f"http://{args.ip}:{args.port}/stop"
    try:
        with urllib.request.urlopen(
            urllib.request.Request(url, method="POST"), timeout=5
        ) as r:
            r.read()
    except (urllib.error.URLError, OSError) as e:
        _out(f"Error: cannot undeploy {args.ip}:{args.port}: {e}")
        return 1
    _out(f"Undeployed engine server at {args.ip}:{args.port}.")
    return 0


def cmd_upgrade(args, storage: Storage) -> int:
    """The reference phones home for new versions (WorkflowUtils.scala:
    220-225); this build is offline, so report the installed version."""
    _out(f"pio-tpu {__version__} — no network egress; upgrade checks "
         "are disabled in this environment.")
    return 0


def cmd_status(args, storage: Storage) -> int:
    """Sanity-check env + storage (console/Console.scala:1028-1085)."""
    _out(f"predictionio_tpu {__version__}")
    # probe the accelerator in a BOUNDED subprocess: a down TPU tunnel
    # hangs backend init inside this process, and `status` is exactly
    # the command an operator runs to diagnose that — it must answer
    import subprocess

    if args.probe_timeout <= 0:
        _out("JAX devices: probe skipped (--probe-timeout 0)")
    else:
        code = (
            "import jax, jax.numpy as jnp\n"
            "x = jnp.ones((8, 8))\n"
            "assert float((x @ x)[0, 0]) == 8.0\n"
            "print('DEVICES=' + repr(jax.devices()))\n"
        )
        try:
            proc = subprocess.run(
                [sys.executable, "-c", code], capture_output=True,
                text=True, timeout=args.probe_timeout,
            )
            for line in proc.stdout.splitlines():
                if line.startswith("DEVICES="):
                    _out(f"JAX devices: {line[len('DEVICES='):]}")
                    break
            else:
                lines = proc.stderr.strip().splitlines()
                # the raised error, not jax's traceback-filter notice
                errs = [
                    ln for ln in lines if "Error" in ln or "error" in ln
                ]
                err = (errs or lines or ["backend init failed"])[-1]
                _out(f"Warning: JAX backend unavailable: {err}")
        except subprocess.TimeoutExpired:
            _out(
                f"Warning: JAX backend init did not answer within "
                f"{args.probe_timeout}s (accelerator tunnel down?); "
                "CPU-only workflows unaffected"
            )
        except Exception as e:  # status must never crash on its own probe
            _out(f"Warning: JAX backend probe failed to run: {e}")
    try:
        storage.verify_all_data_objects()
        _out("Storage: OK (metadata, event store, model data verified)")
    except Exception as e:
        _out(f"Error: storage verification failed: {e}")
        return 1
    # optional accelerators: absent ones degrade to slower pure-Python
    # paths, never to failures — status reports which are active
    from ..native import native_available

    caps = [f"native C++ runtime: {'OK' if native_available() else 'absent'}"]
    for mod, what in (("pandas", "hash-based id dictionaries"),
                      ("pyarrow", "Parquet import/export")):
        try:
            __import__(mod)
            caps.append(f"{mod}: OK ({what})")
        except Exception:
            caps.append(f"{mod}: absent ({what} falls back)")
    _out("Optional fast paths: " + "; ".join(caps))
    _out("Ready.")
    return 0


# --------------------------------------------------------------------------
# parser
# --------------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="pio-tpu",
        description="predictionio_tpu console "
        "(the `pio` command, rebuilt TPU-native)",
    )
    p.add_argument("--version", action="version",
                   version=f"pio-tpu {__version__}")
    p.add_argument("--verbose", action="store_true",
                   help="chatty logging (WorkflowUtils.modifyLogging)")
    p.add_argument("--debug", action="store_true",
                   help="debug logging")
    sub = p.add_subparsers(dest="command", required=True)

    ap = sub.add_parser("app", help="manage apps")
    aps = ap.add_subparsers(dest="app_command", required=True)
    x = aps.add_parser("new")
    x.add_argument("name")
    x.add_argument("--description")
    x.add_argument("--access-key")
    aps.add_parser("list")
    x = aps.add_parser("show")
    x.add_argument("name")
    x = aps.add_parser("delete")
    x.add_argument("name")
    x = aps.add_parser("data-delete")
    x.add_argument("name")
    x.add_argument("--channel")
    x = aps.add_parser("trim", help="delete old events")
    x.add_argument("name")
    x.add_argument("--before", help="delete events before this ISO8601 time")
    x.add_argument("--event", action="append",
                   help="restrict to these event names (repeatable)")
    x.add_argument("--channel")
    x.add_argument("--all", action="store_true",
                   help="also delete $set/$unset/$delete property events")
    x.add_argument("--compact", action="store_true",
                   help="reclaim freed space afterwards (sqlite VACUUM)")
    aps.add_parser("compact",
                   help="reclaim space freed by trims/deletes")
    x = aps.add_parser("channel-new")
    x.add_argument("name")
    x.add_argument("channel")
    x = aps.add_parser("channel-delete")
    x.add_argument("name")
    x.add_argument("channel")

    ak = sub.add_parser("accesskey", help="manage access keys")
    aks = ak.add_subparsers(dest="ak_command", required=True)
    x = aks.add_parser("new")
    x.add_argument("app_name")
    x.add_argument("events", nargs="*")
    x = aks.add_parser("list")
    x.add_argument("app_name", nargs="?")
    x = aks.add_parser("delete")
    x.add_argument("key")

    en = sub.add_parser("engines",
                        help="pio-forge engine registry (built-in "
                        "templates + PIO_TPU_ENGINE_PATH dirs)")
    ens = en.add_subparsers(dest="engines_command", required=True)
    ens.add_parser("list", help="list every registered engine")
    x = ens.add_parser("describe",
                       help="JSON spec of one registered engine")
    x.add_argument("name")

    t = sub.add_parser("train", help="train an engine")
    _add_obs_args(t)
    t.add_argument("--engine-json", default="engine.json")
    t.add_argument("--engine", metavar="NAME",
                   help="train a REGISTERED engine by name (pio-forge "
                   "registry dispatch, no engine.json needed; see "
                   "`pio-tpu engines list`)")
    t.add_argument("--engine-factory")
    t.add_argument("--batch", default="")
    t.add_argument("--skip-sanity-check", action="store_true")
    t.add_argument("--stop-after-read", action="store_true")
    t.add_argument("--stop-after-prepare", action="store_true")
    t.add_argument("--engine-params-key",
                   help="use EngineFactory.engine_params(<key>) instead of "
                   "the engine.json params")
    t.add_argument("--coordinator",
                   help="multi-host: coordinator address host:port")
    t.add_argument("--num-processes", type=int)
    t.add_argument("--process-id", type=int)
    t.add_argument("--scan-cache", action="store_true",
                   help="snapshot columnar event scans to npz keyed by a "
                   "table write-version (storage/scan_cache.py); repeat "
                   "trains on an unchanged table skip the sqlite scan")

    d = sub.add_parser("deploy", help="deploy an engine server")
    _add_obs_args(d)
    d.add_argument("--scan-cache", action="store_true",
                   help="snapshot columnar event scans to npz keyed by a "
                   "table write-version (storage/scan_cache.py)")
    d.add_argument("--engine-json", default="engine.json")
    d.add_argument("--engine", metavar="NAME",
                   help="deploy a REGISTERED engine by name (serves "
                   "the latest instance trained with `train --engine "
                   "NAME`)")
    d.add_argument("--engine-factory")
    d.add_argument("--engine-instance-id")
    d.add_argument("--ip", default="0.0.0.0")
    d.add_argument("--port", type=int, default=8000)
    d.add_argument("--feedback", action="store_true")
    d.add_argument("--event-server-url")
    d.add_argument("--accesskey")
    d.add_argument("--log-url",
                   help="ship serving errors to this URL via POST "
                   "(reference CreateServer remoteLog)")
    d.add_argument("--log-prefix", default="",
                   help="string prepended to each shipped log payload")
    d.add_argument("--microbatch", choices=("auto", "on", "off"),
                   default="auto",
                   help="coalesce concurrent queries into one batched "
                   "device call (auto: when the algorithm batch-"
                   "predicts; off restores bitwise per-request "
                   "determinism)")
    d.add_argument("--shared-batcher", choices=("on", "off"),
                   default="on",
                   help="pio-confluence: ONE shared continuous batcher "
                   "per server — all tenants submit into a single "
                   "queue claimed via weighted deficit round-robin, "
                   "so cross-tenant traffic coalesces onto the "
                   "device (off restores the private batcher per "
                   "tenant)")
    d.add_argument("--query-timeout", type=float, default=None,
                   metavar="SEC",
                   help="per-request time budget: expiry answers a "
                   "structured 503 + Retry-After instead of queueing "
                   "device work behind a client that gave up "
                   "(per-request override: /queries.json?timeout=SEC)")
    d.add_argument("--feedback-capacity", type=int, default=1024,
                   help="bounded feedback/remote-log delivery queue "
                   "size; overflow drops the OLDEST entry and counts "
                   "it in the status JSON")
    d.add_argument("--breaker-failures", type=int, default=5,
                   help="consecutive delivery failures that open the "
                   "circuit breaker for a dead event server / log "
                   "collector")
    d.add_argument("--breaker-reset", type=float, default=10.0,
                   metavar="SEC",
                   help="seconds an open breaker waits before letting "
                   "one probe through")
    d.add_argument("--flight-capacity", type=int, default=None,
                   metavar="N",
                   help="slow-query flight recorder keeps the N "
                   "slowest requests' full span trees (default: "
                   "$PIO_TPU_XRAY_FLIGHT_N or 16; see /debug/xray)")
    d.add_argument("--foldin-poll", type=float, default=None,
                   metavar="SEC",
                   help="pio-live: poll for fold-in delta links every "
                   "SEC seconds and patch them into the serving model "
                   "in place (factor rows + top-k index, no "
                   "stop-the-world reload); pair with a `pio-tpu "
                   "foldin --watch` daemon")
    d.add_argument("--edge", choices=("eventloop", "threads"),
                   default="eventloop",
                   help="serving front end (pio-surge): eventloop = "
                   "one selector loop, no thread per connection "
                   "(default); threads = the stdlib "
                   "ThreadingHTTPServer edge")
    d.add_argument("--max-connections", type=int, default=512,
                   help="concurrent-connection cap; connection "
                   "attempts past it get a structured 503 and are "
                   "closed (slow-loris guard)")
    d.add_argument("--slo-ms", type=float, default=None, metavar="MS",
                   help="pio-lens: latency SLO in milliseconds — arms "
                   "the pio_slo_burn_rate{window} error-budget gauges "
                   "on this server's latency histogram (fleet mode: "
                   "on the router's forward histogram AND every "
                   "replica's serving histogram)")
    d.add_argument("--replicas", type=int, default=0, metavar="N",
                   help="pio-surge fleet mode: spawn N replica "
                   "processes on ephemeral ports and run a router on "
                   "--port fanning out over them with health checks, "
                   "failover masking, and rolling fold-in delta push")
    d.add_argument("--health-interval", type=float, default=1.0,
                   metavar="SEC",
                   help="fleet mode: router health-check period")
    d.add_argument("--push-foldin", type=float, default=None,
                   metavar="SEC",
                   help="fleet mode: run a rolling fold-in delta push "
                   "across the replicas every SEC seconds (each "
                   "replica applies in place, one at a time — "
                   "availability never drops below N-1)")
    d.add_argument("--port-file", metavar="PATH",
                   help="announce the BOUND port (after --port 0 "
                   "resolution) by writing it to PATH — how fleet "
                   "replicas report in")
    d.add_argument("--no-respawn", action="store_true",
                   help="fleet mode: disable the replica-respawn "
                   "supervisor (default: a dead replica process is "
                   "respawned with capped exponential backoff and "
                   "booked in pio_replica_respawns_total)")
    d.add_argument("--multi", metavar="TENANTS_JSON",
                   help="pio-hive: host EVERY tenant of this manifest "
                   "in one process (or one fleet with --replicas): "
                   "lazy load + LRU eviction under a device-memory "
                   "budget, per-tenant breakers/quotas/metrics, and "
                   "weighted sticky A/B variant routing; tenant 0 is "
                   "the pinned anchor")
    d.add_argument("--memory-budget", type=float, default=None,
                   metavar="BYTES",
                   help="override the manifest's memoryBudgetBytes "
                   "(0 = unbounded): resident tenant models are "
                   "LRU-evicted to stay under it; pinned and "
                   "in-flight tenants are never evicted")
    d.add_argument("--autopilot", metavar="ON|JSON",
                   help="pio-pilot: run the SPRT auto-weight "
                   "controller on this registry's experiments "
                   "('on' for defaults, or a JSON knob dict — "
                   "alpha/beta/minLift/minSamples/maxStep/minWeight/"
                   "burnThreshold; requires --multi); every decision "
                   "lands in a pio-tower manifest and at "
                   "GET /debug/experiments")

    fi = sub.add_parser(
        "foldin",
        help="pio-live: fold new events into the deployed model "
        "incrementally (no full retrain)",
    )
    _add_obs_args(fi)
    fi.add_argument("--engine-json", default="engine.json")
    fi.add_argument("--engine", metavar="NAME",
                    help="fold into a REGISTERED engine by name")
    fi.add_argument("--engine-factory")
    fi.add_argument("--engine-instance-id",
                    help="fold into this instance (default: latest "
                    "completed)")
    fi.add_argument("--channel", type=int, default=0)
    fi.add_argument("--watch", action="store_true",
                    help="keep running: poll the event-store watermark "
                    "and fold in whenever it advances")
    fi.add_argument("--interval", type=float, default=5.0,
                    metavar="SEC",
                    help="watch-mode poll period (default 5s)")
    fi.add_argument("--max-cycles", type=int, default=None,
                    help="stop --watch after N non-empty fold-in "
                    "cycles (smoke/bench harnesses)")
    fi.add_argument("--from-now", action="store_true",
                    help="on the FIRST run (no watermark, no chain): "
                    "start the cursor at the store's current high-water "
                    "mark instead of re-folding the history the full "
                    "train already saw")

    e = sub.add_parser("eval", help="run an evaluation sweep")
    _add_obs_args(e)
    e.add_argument("evaluation", nargs="?",
                   help="dotted path to an Evaluation (or factory); "
                   "omit with --engine NAME to run the registered "
                   "engine's declared evaluation")
    e.add_argument("--engine", metavar="NAME",
                   help="run the evaluation a REGISTERED engine "
                   "declares in its spec")
    e.add_argument("engine_params_generator", nargs="?",
                   help="dotted path to an EngineParamsGenerator")
    e.add_argument("--batch", default="")
    e.add_argument("--parallelism", type=int, default=1,
                   help="candidates scored concurrently (>1 disables "
                        "FastEval prefix caching)")
    e.add_argument("--scan-cache", action="store_true",
                   help="snapshot columnar event scans to npz keyed by a "
                   "table write-version (storage/scan_cache.py)")

    ev = sub.add_parser("eventserver", help="run the event server")
    _add_obs_args(ev)
    ev.add_argument("--ip", default="0.0.0.0")
    ev.add_argument("--port", type=int, default=7070)
    ev.add_argument("--stats", action="store_true", default=True)
    ev.add_argument("--write-retries", type=int, default=3,
                    help="attempts (first try included) for a transient "
                    "storage failure before the route answers 503 + "
                    "Retry-After")
    ev.add_argument("--write-backoff", type=float, default=0.05,
                    metavar="SEC",
                    help="base backoff between storage retries "
                    "(decorrelated jitter grows it toward a 10x cap)")
    ev.add_argument("--max-connections", type=int, default=512,
                    help="concurrent-connection cap; attempts past it "
                    "get a structured 503 and are closed")
    # pio-levee: fault-isolated multi-process ingest
    ev.add_argument("--workers", type=int, default=0, metavar="N",
                    help="boot N shard-owner worker processes (each "
                    "owning shard %% N == index of the sharded store, "
                    "each with its own ingest WAL) behind an ingest "
                    "router in this process; 0/1 = single process")
    ev.add_argument("--wal-dir", metavar="DIR",
                    help="group-commit ingest WAL root: events are "
                    "fsynced here before the 2xx and drained to sqlite "
                    "in the background; a crash replays the tail on "
                    "next boot (off by default: ack = sqlite commit)")
    ev.add_argument("--no-wal-fsync", action="store_true",
                    help="skip the per-group fsync (faster, but a HOST "
                    "crash may lose the last commit interval; a mere "
                    "process crash still replays everything)")
    ev.add_argument("--ttl", type=float, metavar="SEC",
                    help="purge events older than SEC on a maintenance "
                    "timer (bounded live window)")
    ev.add_argument("--compact-interval", type=float, metavar="SEC",
                    help="VACUUM owned shard files every SEC (reclaims "
                    "TTL-purged space; off by default)")
    ev.add_argument("--owned-shards", metavar="CSV",
                    help="restrict writes to these shard indexes "
                    "(shard-owner worker mode; e.g. 0,2,4)")
    ev.add_argument("--worker-index", type=int, metavar="I",
                    help="this worker's index in a --workers fleet "
                    "(stripes ownership: shard %% count == I)")
    ev.add_argument("--worker-count", type=int, default=1, metavar="N",
                    help="fleet size for --worker-index striping")
    ev.add_argument("--port-file", metavar="PATH",
                    help="write the bound port here after bind "
                    "(--port 0 = ephemeral; the fleet spawner reads it)")
    ev.add_argument("--no-respawn", action="store_true",
                    help="with --workers: do not respawn dead workers "
                    "(the chaos suite wants corpses to stay dead)")
    ev.add_argument("--slo-ms", type=float, default=None, metavar="MS",
                    help="event-write latency SLO: arms the multi-"
                    "window pio_slo_burn_rate gauges over the event-"
                    "write histogram (with --workers, each shard owner "
                    "arms its own)")

    ad = sub.add_parser("adminserver", help="run the admin API server")
    _add_obs_args(ad)
    ad.add_argument("--ip", default="127.0.0.1")
    ad.add_argument("--port", type=int, default=7071)

    db = sub.add_parser("dashboard", help="run the evaluation dashboard")
    _add_obs_args(db)
    db.add_argument("--ip", default="127.0.0.1")
    db.add_argument("--port", type=int, default=9000)

    im = sub.add_parser("import",
                        help="import events (JSON-lines, .npz columnar, "
                        "or .parquet)")
    im.add_argument("--appid", type=int, required=True)
    im.add_argument("--channel", type=int, default=0)
    im.add_argument("--input", required=True)

    ex = sub.add_parser("export", help="export events to a file")
    ex.add_argument("--appid", type=int, required=True)
    ex.add_argument("--channel", type=int, default=0)
    ex.add_argument("--output", required=True)
    ex.add_argument("--format", choices=["json", "columnar", "parquet"],
                    help="default: json; columnar if output is .npz, "
                    "parquet if .parquet")

    tp = sub.add_parser("template", help="engine template gallery")
    tps = tp.add_subparsers(dest="template_command", required=True)
    tl = tps.add_parser("list")
    tl.add_argument("--index-url", metavar="URL",
                    help="browse a REMOTE JSON template index instead "
                    "of the built-in gallery (Template.scala:130-170 "
                    "analogue)")
    x = tps.add_parser("get")
    x.add_argument("name")
    x.add_argument("directory", nargs="?")
    x.add_argument("--from-archive", metavar="PATH",
                   help="scaffold from a local zip/tar engine archive "
                   "instead of the built-in gallery")
    x.add_argument("--from-url", metavar="URL",
                   help="download a zip/tar engine archive over "
                   "http(s) and scaffold from it (the remote half of "
                   "the reference's template download, "
                   "Template.scala:171-300)")
    x.add_argument("--index-url", metavar="URL",
                   help="look NAME up in a remote JSON template index "
                   "and download its archive")

    b = sub.add_parser("build", help="validate + register an engine")
    b.add_argument("--engine-json", default="engine.json")
    b.add_argument("--engine-factory")
    b.add_argument("--engine-version", default="1")

    ur = sub.add_parser("unregister", help="remove an engine manifest")
    ur.add_argument("--engine-json", default="engine.json")
    ur.add_argument("--engine-version", default="1")

    rn = sub.add_parser("run", help="run a dotted-path main under the env")
    rn.add_argument("main_class")
    rn.add_argument("args", nargs="*")

    ud = sub.add_parser("undeploy", help="stop a deployed engine server")
    ud.add_argument("--ip", default="127.0.0.1")
    ud.add_argument("--port", type=int, default=8000)

    sub.add_parser("upgrade", help="check for framework upgrades")
    stp = sub.add_parser("status", help="check environment and storage")
    stp.add_argument("--probe-timeout", type=float, default=30.0,
                     help="seconds to wait for accelerator backend init "
                     "before reporting it unreachable (status must "
                     "never hang on a dead tunnel)")
    sub.add_parser("version")
    sub.add_parser("help", help="show this help")
    return p


_DISPATCH = {
    "app": cmd_app,
    "accesskey": cmd_accesskey,
    "engines": cmd_engines,
    "train": cmd_train,
    "deploy": cmd_deploy,
    "foldin": cmd_foldin,
    "eval": cmd_eval,
    "eventserver": cmd_eventserver,
    "adminserver": cmd_adminserver,
    "dashboard": cmd_dashboard,
    "import": cmd_import,
    "export": cmd_export,
    "template": cmd_template,
    "build": cmd_build,
    "unregister": cmd_unregister,
    "run": cmd_run,
    "undeploy": cmd_undeploy,
    "upgrade": cmd_upgrade,
    "status": cmd_status,
}


def main(argv: Optional[list[str]] = None,
         storage: Optional[Storage] = None) -> int:
    args = build_parser().parse_args(argv)
    from ..tools.template_gallery import TemplateVersionError
    from ..utils.logging import setup_logging

    setup_logging(verbose=args.verbose, debug=args.debug)
    if args.command == "version":
        _out(f"pio-tpu {__version__}")
        return 0
    if args.command == "help":
        build_parser().print_help()
        return 0
    _apply_obs_flags(args)
    storage = storage or get_storage()
    try:
        return _DISPATCH[args.command](args, storage)
    except TemplateVersionError as e:
        _out(f"Error: {e}")
        return 1


if __name__ == "__main__":
    sys.exit(main())
