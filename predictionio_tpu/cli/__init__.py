"""CLI console (reference `tools/console/`)."""

from .main import load_engine_from_variant, main, resolve_attr

__all__ = ["load_engine_from_variant", "main", "resolve_attr"]
