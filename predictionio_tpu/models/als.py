"""Block ALS matrix factorization on TPU.

The TPU-native replacement for Spark MLlib's ``ALS.train`` /
``ALS.trainImplicit`` that the reference's recommendation-family templates
invoke (`/root/reference/examples/scala-parallel-recommendation/custom-query/
src/main/scala/ALSAlgorithm.scala`, similarproduct, ecommerce).  The MLlib
implementation block-partitions factors across Spark executors and shuffles
factor blocks each half-iteration (SURVEY §2.7(2)); here the whole problem is
HBM-resident and each half-iteration is a handful of batched XLA calls:

* Host preprocessing groups rows into **power-of-two padded buckets**
  (ALX-style, arXiv 2112.02194): every row's rating list is padded to the
  bucket width K, so the device sees only static-shape dense arrays.
  Padding waste is bounded by 2x; bucket count is O(log max_count), so at
  most ~12 compiled shapes per direction.
* Per bucket, one fused XLA computation: gather opposite factors
  ``[B, K, R]`` -> masked Gram matrices via einsum (MXU) -> batched
  Cholesky solve -> scatter updated factors.
* Sharding: the batch dim of every bucket is sharded over the mesh's
  ``data`` axis; factor tables are replicated, so the gather is local and
  the update is an all-gather-free scatter into the replicated table —
  XLA inserts the collectives from the shardings (no NCCL/MPI analogue
  needed).

Both regularization conventions are implemented:

* ``explicit``  — least squares with ALS-WR weighted-λ (λ·n_row·I), which is
  Spark MLlib 1.3's convention; RMSE-parity target per BASELINE.md.
* ``implicit``  — Hu-Koren-Volinsky confidence weighting c = 1 + α·r
  (``ALS.trainImplicit`` parity: the default of the reference templates).
"""

from __future__ import annotations

import functools
import logging
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import DATA_AXIS, pad_to_multiple
from ..storage.columnar import Ratings

logger = logging.getLogger(__name__)

__all__ = ["ALSConfig", "ALSFactors", "train_als", "rmse", "Buckets"]


@dataclass(frozen=True)
class ALSConfig:
    rank: int = 10
    num_iterations: int = 20
    lam: float = 0.01
    implicit: bool = False
    alpha: float = 1.0
    seed: int = 3
    # λ·n_row·I (MLlib <=1.3 / ALS-WR) vs plain λ·I
    weighted_lambda: bool = True
    # truncate pathological rows beyond this many ratings (0 = no cap)
    max_ratings_per_row: int = 0
    min_bucket_k: int = 8
    compute_dtype: str = "float32"


@dataclass
class ALSFactors:
    """The trained model: factor matrices as host arrays."""

    user_factors: np.ndarray  # [n_users, rank] float32
    item_factors: np.ndarray  # [n_items, rank] float32


# --------------------------------------------------------------------------
# Host-side preprocessing: COO -> power-of-two padded buckets per direction
# --------------------------------------------------------------------------


@dataclass
class Bucket:
    rows: np.ndarray   # [B]    row ids whose systems this bucket solves
    idx: np.ndarray    # [B, K] opposite-side indices (0-padded)
    val: np.ndarray    # [B, K] ratings (0-padded)
    mask: np.ndarray   # [B, K] 1.0 where a real rating


@dataclass
class Buckets:
    n_rows: int
    buckets: list[Bucket]


def build_buckets(
    row_ix: np.ndarray,
    col_ix: np.ndarray,
    val: np.ndarray,
    n_rows: int,
    min_k: int = 8,
    max_per_row: int = 0,
) -> Buckets:
    """Group rows by padded rating-count so the device solves static shapes.

    Rows with zero ratings are excluded (their factors stay at init, like
    MLlib which simply never solves them).
    """
    order = np.argsort(row_ix, kind="stable")
    c_sorted = col_ix[order]
    v_sorted = val[order]
    counts = np.bincount(row_ix, minlength=n_rows)
    starts = np.zeros(n_rows + 1, dtype=np.int64)
    np.cumsum(counts, out=starts[1:])

    if max_per_row and max_per_row > 0:
        eff_counts = np.minimum(counts, max_per_row)
    else:
        eff_counts = counts

    # bucket key: next power of two of the (possibly capped) count —
    # computed for all rows at once (ceil(log2), floored at min_k)
    safe = np.maximum(eff_counts, 1)
    k_of_row = np.maximum(
        min_k, 1 << np.ceil(np.log2(safe)).astype(np.int64)
    )
    active = np.nonzero(counts)[0]
    k_active = k_of_row[active]

    out: list[Bucket] = []
    n_total = len(c_sorted)
    for k in np.unique(k_active):
        k = int(k)
        rows = active[k_active == k].astype(np.int32)
        # gather each row's slice via a [B, k] position grid; out-of-range
        # positions are clipped and masked off
        pos = starts[rows][:, None] + np.arange(k, dtype=np.int64)[None, :]
        valid = np.arange(k)[None, :] < eff_counts[rows][:, None]
        pos = np.minimum(pos, n_total - 1)
        idx = np.where(valid, c_sorted[pos], 0).astype(np.int32)
        vals = np.where(valid, v_sorted[pos], 0.0).astype(np.float32)
        out.append(
            Bucket(
                rows=rows, idx=idx, val=vals,
                mask=valid.astype(np.float32),
            )
        )
    return Buckets(n_rows=n_rows, buckets=out)


# --------------------------------------------------------------------------
# Device-side solves
# --------------------------------------------------------------------------


@functools.partial(
    jax.jit, static_argnames=("implicit", "weighted_lambda")
)
def _solve_bucket(
    opp_factors: jax.Array,  # [M, R] opposite-side factor table (replicated)
    gram: jax.Array,         # [R, R] YtY (used only for implicit)
    idx: jax.Array,          # [B, K]
    val: jax.Array,          # [B, K]
    mask: jax.Array,         # [B, K]
    lam: jax.Array,          # scalar
    alpha: jax.Array,        # scalar
    *,
    implicit: bool,
    weighted_lambda: bool,
) -> jax.Array:
    """One normal-equation solve per row of the bucket (batched)."""
    r = opp_factors.shape[-1]
    V = opp_factors[idx]                       # [B, K, R] gather
    Vm = V * mask[..., None]
    n_row = jnp.sum(mask, axis=-1)             # [B]
    if implicit:
        # A = YtY + sum alpha*r v v^T + reg;  b = sum (1 + alpha*r) v
        cw = alpha * val * mask                # (c - 1)
        A = gram + jnp.einsum("bk,bkr,bks->brs", cw, Vm, Vm)
        b = jnp.einsum("bk,bkr->br", (1.0 + cw) * mask, Vm)
    else:
        A = jnp.einsum("bkr,bks->brs", Vm, Vm)
        b = jnp.einsum("bk,bkr->br", val * mask, Vm)
    if weighted_lambda:
        reg = lam * jnp.maximum(n_row, 1.0)        # ALS-WR: λ·n_row
    else:
        reg = jnp.full_like(n_row, lam)
    A = A + reg[:, None, None] * jnp.eye(r, dtype=A.dtype)
    # batched SPD solve via Cholesky
    L = jax.lax.linalg.cholesky(A)
    y = jax.lax.linalg.triangular_solve(
        L, b[..., None], left_side=True, lower=True
    )
    x = jax.lax.linalg.triangular_solve(
        L, y, left_side=True, lower=True, transpose_a=True
    )
    return x[..., 0]                           # [B, R]


def _half_iteration(
    factors_to_update: jax.Array,
    opp_factors: jax.Array,
    device_buckets,
    cfg: ALSConfig,
) -> jax.Array:
    if cfg.implicit:
        gram = opp_factors.T @ opp_factors
    else:
        gram = jnp.zeros(
            (opp_factors.shape[1], opp_factors.shape[1]), opp_factors.dtype
        )
    lam = jnp.asarray(cfg.lam, opp_factors.dtype)
    alpha = jnp.asarray(cfg.alpha, opp_factors.dtype)
    for rows, idx, val, mask in device_buckets:
        x = _solve_bucket(
            opp_factors, gram, idx, val, mask, lam, alpha,
            implicit=cfg.implicit, weighted_lambda=cfg.weighted_lambda,
        )
        x = x[: rows.shape[0]]                 # drop batch padding
        factors_to_update = factors_to_update.at[rows].set(x)
    return factors_to_update


def _stage_buckets(
    buckets: Buckets,
    mesh: Optional[Mesh],
    max_entries_per_call: int = 4 << 20,
):
    """Move bucket arrays to device once, padding the batch dim to the mesh
    size and sharding it over the data axis.

    Buckets whose B*K exceeds ``max_entries_per_call`` are split into
    chunks so the gathered ``[B, K, R]`` intermediate stays within a fixed
    HBM budget regardless of dataset size (splitting reuses the same
    compiled executable because K and the chunk shapes repeat).
    """
    n_dev = mesh.size if mesh is not None else 1
    staged = []
    for b in buckets.buckets:
        k = b.idx.shape[1]
        b_cap = max(n_dev, (max_entries_per_call // k) // n_dev * n_dev)
        for s in range(0, len(b.rows), b_cap):
            rows = b.rows[s : s + b_cap]
            B = len(rows)
            Bp = pad_to_multiple(max(B, n_dev), n_dev)
            idx = np.zeros((Bp, k), b.idx.dtype)
            val = np.zeros((Bp, k), b.val.dtype)
            mask = np.zeros((Bp, k), b.mask.dtype)
            idx[:B] = b.idx[s : s + b_cap]
            val[:B] = b.val[s : s + b_cap]
            mask[:B] = b.mask[s : s + b_cap]
            if mesh is not None and mesh.size > 1:
                sh = NamedSharding(mesh, P(DATA_AXIS, None))
                idx = jax.device_put(idx, sh)
                val = jax.device_put(val, sh)
                mask = jax.device_put(mask, sh)
            else:
                idx, val, mask = map(jnp.asarray, (idx, val, mask))
            staged.append((jnp.asarray(rows), idx, val, mask))
    return staged


def train_als(
    ratings: Ratings | tuple[np.ndarray, np.ndarray, np.ndarray],
    n_users: Optional[int] = None,
    n_items: Optional[int] = None,
    cfg: ALSConfig = ALSConfig(),
    mesh: Optional[Mesh] = None,
) -> ALSFactors:
    """Run ALS to convergence budget; returns host factor arrays."""
    if isinstance(ratings, Ratings):
        u, i, v = ratings.user_ix, ratings.item_ix, ratings.rating
        n_users = ratings.n_users
        n_items = ratings.n_items
    else:
        u, i, v = ratings
        assert n_users is not None and n_items is not None

    user_buckets = build_buckets(
        u, i, v, n_users, cfg.min_bucket_k, cfg.max_ratings_per_row
    )
    item_buckets = build_buckets(
        i, u, v, n_items, cfg.min_bucket_k, cfg.max_ratings_per_row
    )
    dev_user_buckets = _stage_buckets(user_buckets, mesh)
    dev_item_buckets = _stage_buckets(item_buckets, mesh)

    # MLlib-style init: N(0, 1)/sqrt(rank) scaled factors, fixed seed
    key = jax.random.PRNGKey(cfg.seed)
    ku, ki = jax.random.split(key)
    dtype = jnp.dtype(cfg.compute_dtype)
    U = jax.random.normal(ku, (n_users, cfg.rank), dtype) / jnp.sqrt(cfg.rank)
    V = jax.random.normal(ki, (n_items, cfg.rank), dtype) / jnp.sqrt(cfg.rank)
    if mesh is not None and mesh.size > 1:
        rep = NamedSharding(mesh, P())
        U = jax.device_put(U, rep)
        V = jax.device_put(V, rep)

    for it in range(cfg.num_iterations):
        U = _half_iteration(U, V, dev_user_buckets, cfg)
        V = _half_iteration(V, U, dev_item_buckets, cfg)
        logger.debug("ALS iteration %d/%d done", it + 1, cfg.num_iterations)
    U.block_until_ready()
    return ALSFactors(
        user_factors=np.asarray(U), item_factors=np.asarray(V)
    )


# --------------------------------------------------------------------------
# Quality metrics
# --------------------------------------------------------------------------


@jax.jit
def _sq_err_sum(U, V, u, i, v):
    pred = jnp.sum(U[u] * V[i], axis=-1)
    d = pred - v
    return jnp.sum(d * d)


def rmse(
    factors: ALSFactors,
    user_ix: np.ndarray,
    item_ix: np.ndarray,
    rating: np.ndarray,
    chunk: int = 1 << 20,
) -> float:
    """RMSE over COO triples, chunked to bound device memory."""
    U = jnp.asarray(factors.user_factors)
    V = jnp.asarray(factors.item_factors)
    total = 0.0
    n = len(rating)
    for s in range(0, n, chunk):
        e = min(s + chunk, n)
        total += float(
            _sq_err_sum(
                U, V,
                jnp.asarray(user_ix[s:e]),
                jnp.asarray(item_ix[s:e]),
                jnp.asarray(rating[s:e]),
            )
        )
    return float(np.sqrt(total / max(n, 1)))
