"""Block ALS matrix factorization on TPU.

The TPU-native replacement for Spark MLlib's ``ALS.train`` /
``ALS.trainImplicit`` that the reference's recommendation-family templates
invoke (`/root/reference/examples/scala-parallel-recommendation/custom-query/
src/main/scala/ALSAlgorithm.scala`, similarproduct, ecommerce).  The MLlib
implementation block-partitions factors across Spark executors and shuffles
factor blocks each half-iteration (SURVEY §2.7(2)); here the whole problem is
HBM-resident and each half-iteration is ONE XLA computation:

* Host preprocessing groups rows into **power-of-two padded buckets**
  (ALX-style, arXiv 2112.02194): rows are keyed by next-pow2(rating count),
  so the device sees only static shapes.  Only the time-sorted COO arrays
  and tiny per-bucket ``(rows, starts, counts)`` vectors are transferred;
  the padded ``[B, K]`` rating blocks are expanded **on device** inside the
  compiled program (a gather from the sorted COO), which cuts host->HBM
  traffic ~3x and keeps the expansion fused with the solves.
* Per bucket, inside the same program: gather opposite factors
  ``[B, K, R]`` -> masked Gram matrices via einsum (MXU) -> batched
  Cholesky solve -> masked scatter into the factor table (OOB rows from
  batch padding are dropped).
* The whole half-iteration is a single ``jit`` with the factor table
  donated, so a 20-iteration train is 40 dispatches and exactly 2 compiled
  executables (one per direction) regardless of bucket count.
* Sharding: bucket batch dims are sharded over the mesh's ``data`` axis;
  factor tables and the COO arrays are replicated, so gathers are local
  and XLA inserts the collectives for the scatter from the shardings
  (no NCCL/MPI analogue needed).

Both regularization conventions are implemented:

* ``explicit``  — least squares with ALS-WR weighted-λ (λ·n_row·I), which is
  Spark MLlib 1.3's convention; RMSE-parity target per BASELINE.md.
* ``implicit``  — Hu-Koren-Volinsky confidence weighting c = 1 + α·r
  (``ALS.trainImplicit`` parity: the default of the reference templates).
"""

from __future__ import annotations

import functools
import logging
import math
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..obs import TRAIN_PHASE_SECONDS, tower, xray
from ..parallel.mesh import DATA_AXIS, fence, pad_to_multiple, replicated
from ..storage.columnar import Ratings

logger = logging.getLogger(__name__)

# cap on the grouped-gather slab intermediate ([chunk, K, G, R]): the
# slab is G (8-16) times the row gather's output, so it's produced in
# row-chunks of at most this many bytes and shrunk back to [*, K, R] by
# the in-slab select before the next chunk materializes
_GROUPED_SLAB_BYTES = 256 * 1024 * 1024

__all__ = [
    "ALSConfig",
    "ALSFactors",
    "ALSTrainer",
    "train_als",
    "sweep_train_als",
    "rmse",
    "BucketLayout",
    "build_bucket_layout",
]

# cap on B*K entries of a single bucket chunk: bounds the [B, K, R]
# gathered intermediate (~1 GiB at rank 64, f32) regardless of dataset size
MAX_ENTRIES_PER_BUCKET = 4 << 20


@dataclass(frozen=True)
class ALSConfig:
    rank: int = 10
    num_iterations: int = 20
    lam: float = 0.01
    implicit: bool = False
    alpha: float = 1.0
    seed: int = 3
    # λ·n_row·I (MLlib <=1.3 / ALS-WR) vs plain λ·I
    weighted_lambda: bool = True
    # truncate pathological rows beyond this many ratings (0 = no cap)
    max_ratings_per_row: int = 0
    min_bucket_k: int = 8
    # storage dtype of the factor tables themselves (init + iterates).
    # Whatever this is, Gram accumulation, regularization, and the SPD
    # solves always run in f32 (bf16 normal equations are numerically
    # unsafe); use gather_dtype to cut the hot gather's bandwidth instead
    compute_dtype: str = "float32"
    # MXU precision for the Gram einsums: "highest" (f32), "high" (bf16x3),
    # "default" (bf16).  RMSE parity wants "highest"; ranking-only workloads
    # can trade down.
    matmul_precision: str = "highest"
    # batched SPD solver: "xla" (lax.linalg), "pallas" (ops/solve.py
    # Gauss-Jordan kernel for the solves alone), or "fused"
    # (ops/fused_als.py single-pass gather+Gram+solve kernel on sides
    # whose opposite table fits VMEM; other sides fall back to xla)
    solver: str = "xla"
    # in-kernel gather form of the fused kernel (solver="fused" only):
    # "taa" = same-shape take_along_axis(axis=0) sub-gathers (Mosaic
    # tpu.dynamic_gather), "dma" = scalar-prefetched rolling-window
    # async row copies, "auto" = per-backend compile-and-run probe
    # (ops/fused_als.resolve_gather_impl; docs/PERF_PLAN.md §4).  The
    # resolved value lands in bench artifacts as fused_gather_resolved.
    fused_gather: str = "auto"
    # rank-sweep strategy: "full" solves the complete R×R normal
    # equations per row (today's behavior, the default); "subspace"
    # (iALS++, arXiv 2110.14044) sweeps the rank dimension in blocks of
    # ``subspace_size``, replacing each per-row O(R³) SPD solve with
    # R/B solves of B×B subsystems and the full [K,R]→R² Gram
    # contraction with rank-B updates against a cached residual —
    # per sweep: Gram work drops R/B-fold, solve work (R/B)²-fold.
    # ``subspace_size >= rank`` routes through the EXACT full-solve
    # code path (bitwise-identical results).
    solver_mode: str = "full"
    # block width B of the subspace sweep (ALX-friendly: smaller B×B
    # systems pack MORE rows per VMEM tile in the Pallas GJ kernel)
    subspace_size: int = 16
    # dtype the opposite factor table is GATHERED in: "float32" (exact,
    # default) or "bfloat16" — the Gram einsums are gather-bandwidth-bound
    # (see docs/ARCHITECTURE.md cost model), so a bf16 table halves the
    # bytes the hot gather moves (and the ICI all-gather in sharded mode)
    # at a small accuracy cost; solves and accumulation stay f32
    gather_dtype: str = "float32"
    # how the opposite rows are fetched: "row" (plain jnp.take) or
    # "grouped" — gather TILE-ALIGNED groups of 8 (f32) / 16 (bf16)
    # consecutive rows as one [G*R]-lane slab, then take_along_axis the
    # wanted row.  A rank-64 row is a fraction of one (8,128) memory
    # tile, so the plain row gather can move up to 16x (f32) / 32x
    # (bf16) more bytes than it delivers; grouped reads move whole
    # tiles usefully.  Exact (same rows, same math) — the A/B is pure
    # gather bandwidth, measured on-chip by bench.py --gather-mode.
    gather_mode: str = "row"
    # -- pio-scout serve-time retrieval defaults ------------------------
    # Training never reads these; they ride the config object so one
    # ALSConfig describes a full train+serve deployment (bench.py and
    # programmatic servers configure one place; the templates map the
    # engine.json keys retrieval/candidateFactor/nprobe onto them).
    # "exact" = brute-force scan; "int8" = flat quantized candidate
    # stage + exact f32 rerank; "ivf" = candidates restricted to the
    # nprobe nearest coarse clusters (predictionio_tpu/retrieval/).
    retrieval: str = "exact"
    candidate_factor: int = 10
    nprobe: int = 8

    def __post_init__(self) -> None:
        # checked here, not at use sites: the use sites test exact
        # equality with an else-fallthrough, so a typo'd value would
        # silently run the default path (and these strings now arrive
        # straight from user engine.json files via the templates)
        if self.gather_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"gather_dtype must be 'float32' or 'bfloat16', "
                f"got {self.gather_dtype!r}"
            )
        if self.gather_mode not in ("row", "grouped"):
            raise ValueError(
                f"gather_mode must be 'row' or 'grouped', "
                f"got {self.gather_mode!r}"
            )
        if self.gather_mode == "grouped" and self.solver == "fused":
            # the fused kernel does its own in-kernel access pattern —
            # accepting the combination would record gather_mode=grouped
            # in bench artifacts while actually measuring the fused path
            raise ValueError(
                "gather_mode='grouped' does not compose with "
                "solver='fused' (the fused kernel gathers in-kernel); "
                "pick one"
            )
        if self.solver not in ("xla", "pallas", "fused"):
            raise ValueError(
                f"solver must be 'xla', 'pallas' or 'fused', "
                f"got {self.solver!r}"
            )
        if self.fused_gather not in ("auto", "taa", "dma"):
            raise ValueError(
                f"fused_gather must be 'auto', 'taa' or 'dma', "
                f"got {self.fused_gather!r}"
            )
        if self.fused_gather != "auto" and self.solver != "fused":
            # an explicit gather form with a non-fused solver would be
            # silently ignored — the same foot-gun class as the other
            # exact-equality knobs above
            raise ValueError(
                f"fused_gather={self.fused_gather!r} only applies to "
                "solver='fused'"
            )
        if self.solver_mode not in ("full", "subspace"):
            raise ValueError(
                f"solver_mode must be 'full' or 'subspace', "
                f"got {self.solver_mode!r}"
            )
        if self.solver_mode == "subspace":
            if self.subspace_size < 1:
                raise ValueError(
                    f"subspace_size must be >= 1, got {self.subspace_size}"
                )
            if self.solver == "fused":
                # the fused kernel is a single-pass full-rank
                # gather+Gram+solve — there is no block-sweep variant of
                # it; accepting the combination would silently run the
                # full solve while the config claims subspace
                raise ValueError(
                    "solver_mode='subspace' does not compose with "
                    "solver='fused' (the fused kernel solves the full "
                    "R×R system in-kernel); use solver='pallas' or "
                    "'xla'"
                )
        if self.factor_placement not in ("replicated", "sharded"):
            raise ValueError(
                f"factor_placement must be 'replicated' or 'sharded', "
                f"got {self.factor_placement!r}"
            )
        if self.loss_every is not None and self.loss_every < 0:
            raise ValueError(
                f"loss_every must be >= 0, got {self.loss_every}"
            )
        if self.retrieval not in ("exact", "int8", "ivf"):
            raise ValueError(
                f"retrieval must be 'exact', 'int8' or 'ivf', "
                f"got {self.retrieval!r}"
            )
        if self.candidate_factor < 1:
            raise ValueError(
                f"candidate_factor must be >= 1, "
                f"got {self.candidate_factor}"
            )
        if self.nprobe < 1:
            raise ValueError(f"nprobe must be >= 1, got {self.nprobe}")
        if self.coded_shards:
            if self.factor_placement != "sharded":
                raise ValueError(
                    "coded_shards=True requires "
                    "factor_placement='sharded' (parity is a property "
                    "of the sharded table layout)"
                )
            if self.solver_mode == "subspace":
                # the subspace sweep transiently all-gathers the
                # UPDATING table too; serving that gather from parity as
                # well is a second code word this PR does not maintain —
                # refuse rather than silently run uncoded
                raise ValueError(
                    "coded_shards=True does not compose with "
                    "solver_mode='subspace' (the warm-start gather of "
                    "the updating table is not parity-protected)"
                )
    # factor-table placement on the mesh: "replicated" keeps both tables
    # on every device (fastest when they fit one chip's HBM); "sharded"
    # block-shards both tables over the ``data`` axis (ALX-style, arXiv
    # 2112.02194) so trainable model size scales with mesh HBM — the
    # opposite table is all-gathered transiently per half-iteration and
    # updates are written shard-locally
    factor_placement: str = "replicated"
    # coded-ALS straggler tolerance (arXiv 2105.03631; parallel/coded.py):
    # maintain a rotating parity block alongside the d factor shards so
    # a half-iteration whose shard is late/dead completes from the other
    # d-1 plus parity instead of stalling the ring.  Sharded-only.
    coded_shards: bool = False
    # per-half shard wait budget when coded (seconds): a shard whose
    # injected/observed lag stays within the budget is waited for; past
    # it the shard is served from parity.  0 = no budget — any
    # fault-flagged straggler degrades immediately (the deterministic
    # default the chaos suite pins)
    shard_hop_budget_s: float = 0.0
    # pio-tower sweep-loss cadence: compute training RMSE every N
    # sweeps over a seeded subsample of at most
    # ALSTrainer.LOSS_SAMPLE_MAX triples (one cached _sq_err_sum
    # dispatch — cost bounded at any scale; exact RMSE when the
    # dataset fits the cap).  0 disables; None = auto (every sweep).
    # The convergence watchdog's divergence check needs the loss; its
    # NaN check does not
    loss_every: Optional[int] = None


@dataclass
class ALSFactors:
    """The trained model: factor matrices as host arrays."""

    user_factors: np.ndarray  # [n_users, rank] float32
    item_factors: np.ndarray  # [n_items, rank] float32


# --------------------------------------------------------------------------
# Host-side preprocessing: COO -> bucket layout (indices only; the padded
# [B, K] blocks are expanded on device)
# --------------------------------------------------------------------------


@dataclass
class Bucket:
    k: int             # static pad width (power of two)
    rows: np.ndarray   # [Bp] row ids; padding = n_rows (OOB -> dropped)
    starts: np.ndarray  # [Bp] offset of each row's slice in the sorted COO
    counts: np.ndarray  # [Bp] true rating count (<= k); 0 for padding


@dataclass
class BucketLayout:
    n_rows: int
    col_sorted: np.ndarray  # [nnz] opposite-side ids, grouped by row
    val_sorted: np.ndarray  # [nnz] ratings, grouped by row
    buckets: list[Bucket] = field(default_factory=list)


def build_bucket_layout(
    row_ix: np.ndarray,
    col_ix: np.ndarray,
    val: np.ndarray,
    n_rows: int,
    min_k: int = 8,
    max_per_row: int = 0,
    batch_multiple: int = 1,
    max_entries: Optional[int] = None,
    starts_dtype: type = np.int32,
) -> BucketLayout:
    """Group rows by padded rating-count so the device solves static shapes.

    Rows with zero ratings are excluded (their factors stay at init, like
    MLlib which simply never solves them).  Oversized buckets are split so
    ``B*K <= max_entries``; batch dims are padded to ``batch_multiple``
    (the mesh size) for even sharding.

    ``starts_dtype``: the replicated-COO path keeps int32 (those offsets
    are gathered on device) and rejects COOs past the int32 range; the
    sharded-COO path passes int64 — its device offsets are SHARD-LOCAL
    (``_plan_shard_layout``), so the global layout may exceed 2^31
    ratings as long as every per-device shard stays under it.
    """
    if starts_dtype == np.int32 and len(val) >= np.iinfo(np.int32).max:
        # Bucket.starts (and the on-device gather positions) are int32;
        # beyond 2^31 ratings the offsets would wrap. A single-replica COO
        # that large belongs on the sharded-COO path instead.
        raise ValueError(
            f"{len(val):,} ratings exceed the int32 offset range of a "
            "replicated bucket layout; use factor_placement='sharded' "
            "(sharded COO) or shard the COO across hosts first"
        )
    # O(n) native counting sort when the C++ runtime is available
    # (predictionio_tpu/native), NumPy argsort otherwise
    from ..native import sort_coo_by_row

    c_sorted, v_sorted, counts, starts = sort_coo_by_row(
        row_ix, col_ix, val, n_rows
    )
    layout = BucketLayout(
        n_rows=n_rows, col_sorted=c_sorted, val_sorted=v_sorted
    )
    layout.buckets = _assemble_buckets(
        counts, starts, n_rows, min_k, max_per_row, batch_multiple,
        max_entries, starts_dtype=starts_dtype,
    )
    return layout


def _assemble_buckets(
    counts: np.ndarray,
    starts: np.ndarray,
    n_rows: int,
    min_k: int = 8,
    max_per_row: int = 0,
    batch_multiple: int = 1,
    max_entries: Optional[int] = None,
    starts_dtype: type = np.int32,
) -> list[Bucket]:
    """Bucket plan from per-row (counts, starts) alone.

    Shared by the host path (counts/starts from the counting sort) and the
    device-staging path (counts from ``np.bincount`` on the raw COO, starts
    from its cumsum — the big sorted arrays never touch the host there).
    """
    if max_entries is None:
        max_entries = MAX_ENTRIES_PER_BUCKET
    if max_per_row and max_per_row > 0:
        eff_counts = np.minimum(counts, max_per_row)
    else:
        eff_counts = counts

    # bucket key: next power of two of the (possibly capped) count —
    # computed for all rows at once (ceil(log2), floored at min_k)
    safe = np.maximum(eff_counts, 1)
    k_of_row = np.maximum(
        min_k, 1 << np.ceil(np.log2(safe)).astype(np.int64)
    )
    active = np.nonzero(counts)[0]
    k_active = k_of_row[active]

    buckets: list[Bucket] = []
    for k in np.unique(k_active):
        k = int(k)
        rows_k = active[k_active == k].astype(np.int32)
        b_cap = max(
            batch_multiple,
            (max_entries // k) // batch_multiple * batch_multiple,
        )
        for s in range(0, len(rows_k), b_cap):
            rows = rows_k[s : s + b_cap]
            B = len(rows)
            Bp = pad_to_multiple(max(B, batch_multiple), batch_multiple)
            # padding ids are distinct OOB values (n_rows, n_rows+1, ...):
            # the scatter drops them, and uniqueness stays honest for
            # unique_indices=True
            rows_p = n_rows + np.arange(Bp, dtype=np.int32)
            starts_p = np.zeros(Bp, dtype=starts_dtype)
            counts_p = np.zeros(Bp, dtype=np.int32)
            rows_p[:B] = rows
            starts_p[:B] = starts[rows]
            counts_p[:B] = eff_counts[rows]
            buckets.append(
                Bucket(k=k, rows=rows_p, starts=starts_p, counts=counts_p)
            )
    return buckets


def _plan_shard_layout(
    buckets: list[Bucket], n_dev: int, build_perm: bool = True
) -> tuple[Optional[np.ndarray], list[np.ndarray], int]:
    """Shard-ordered COO plan: co-partition rating slices with the bucket
    rows each device solves.

    ``build_sharded_half`` splits every bucket's batch dim into ``n_dev``
    contiguous chunks (shard_map ``P('data')`` semantics).  This plan
    reorders the row-grouped COO so device ``d`` holds exactly the rating
    slices of the rows in ITS chunks, concatenated bucket by bucket — the
    TPU answer to MLlib's co-partitioned rating/factor blocks
    (`org.apache.spark.ml.recommendation.ALS` block layout; SURVEY
    §2.7(2)) and to ALX's sharded rating matrix (arXiv 2112.02194).

    Returns ``(perm, local_starts, L)``:

    * ``perm`` — ``[n_dev, L]`` int64 gather indices into the row-sorted
      COO; position ``(d, j)`` names the global rating that lands at
      shard-local offset ``j`` on device ``d`` (padding positions gather
      index 0; never read — every device access is masked by counts).
    * ``local_starts`` — per bucket, an int32 ``[Bp]`` array aligned with
      ``bucket.rows`` whose entries are offsets into the OWNING DEVICE's
      shard (replacing the global int32 starts whose range capped nnz).
    * ``L`` — the padded per-shard length (max over devices).

    The per-device nnz imbalance is bounded by one bucket row's worth of
    ratings per bucket (chunks differ by at most the count spread inside
    a bucket, and buckets group rows of similar padded size).

    ``build_perm=False`` skips materializing ``perm`` (total-nnz-sized)
    and returns ``None`` in its place — planning-only validation, e.g.
    checking the per-shard int32 ceiling at >2^31 global nnz without
    allocating the index.
    """
    offsets = np.zeros(n_dev, dtype=np.int64)
    local_starts: list[np.ndarray] = []
    starts_per_dev: list[list[np.ndarray]] = [[] for _ in range(n_dev)]
    counts_per_dev: list[list[np.ndarray]] = [[] for _ in range(n_dev)]
    for b in buckets:
        Bp = len(b.rows)
        assert Bp % n_dev == 0, "bucket batch dim not padded to mesh size"
        chunk = Bp // n_dev
        ls = np.zeros(Bp, dtype=np.int64)
        for d in range(n_dev):
            sl = slice(d * chunk, (d + 1) * chunk)
            cnts = b.counts[sl].astype(np.int64)
            ls[sl] = offsets[d] + np.concatenate(
                ([0], np.cumsum(cnts)[:-1])
            )
            offsets[d] += int(cnts.sum())
            starts_per_dev[d].append(np.asarray(b.starts[sl], np.int64))
            counts_per_dev[d].append(cnts)
        local_starts.append(ls)
    L = int(offsets.max()) if n_dev else 0
    if L >= np.iinfo(np.int32).max:
        raise ValueError(
            f"per-shard nnz {L:,} exceeds the int32 offset range; use "
            "more devices or shard across hosts"
        )
    if not build_perm:
        return None, [ls.astype(np.int32) for ls in local_starts], max(L, 1)
    perm = np.zeros((n_dev, max(L, 1)), dtype=np.int64)
    for d in range(n_dev):
        if not starts_per_dev[d]:
            continue
        starts_d = np.concatenate(starts_per_dev[d])
        counts_d = np.concatenate(counts_per_dev[d])
        total = int(counts_d.sum())
        if total:
            # vectorized multi-slice gather: for each row j, positions
            # starts_d[j] .. starts_d[j]+counts_d[j]-1 in order
            base = np.repeat(
                starts_d
                - np.concatenate(([0], np.cumsum(counts_d)[:-1])),
                counts_d,
            )
            perm[d, :total] = np.arange(total, dtype=np.int64) + base
    return perm, [ls.astype(np.int32) for ls in local_starts], max(L, 1)


@xray.instrument("als.expand_sides")
@jax.jit
def _device_expand_sides(col_by_row, val_by_row, row_counts, val_scale):
    """Both sides' row-grouped ``(c_sorted, v_sorted)`` from a COO the
    HOST already counting-sorted by row (staging="device").

    Input is only ``(col_by_row, val_by_row, row_counts)`` in the
    narrowest lossless dtypes — the row-id column never crosses the
    host↔device link at all: the row side's grouping is the transfer
    order itself, and the row ids are reconstructed on device as
    ``repeat(arange(n_rows), row_counts)`` (exact because the host sort
    is ascending-stable).  The opposite side is one argsort over the col
    ids + gathers; the value decode to f32 happens after its gather so
    that big move stays narrow.  Ordering within a row is arbitrary,
    which the bucket layout permits.
    """
    nnz = col_by_row.shape[0]
    c_row = col_by_row.astype(jnp.int32)
    v_row = val_by_row.astype(jnp.float32) * val_scale
    rows = jnp.repeat(
        jnp.arange(row_counts.shape[0], dtype=jnp.int32), row_counts,
        total_repeat_length=nnz,
    )
    order = jnp.argsort(c_row)
    c_opp = jnp.take(rows, order)
    # value decode happens after its gather so that big move stays uint8
    v_opp = jnp.take(val_by_row, order).astype(jnp.float32) * val_scale
    return c_row, v_row, c_opp, v_opp


# --------------------------------------------------------------------------
# Device-side: one jitted half-iteration per direction
# --------------------------------------------------------------------------


def _spd_solve(A: jax.Array, b: jax.Array, solver: str) -> jax.Array:
    """Batched SPD solve ``A[i] x[i] = b[i]`` via the configured backend.

    One routing point for BOTH the full R×R systems and the subspace
    mode's B×B subsystems: ``"pallas"`` runs the Gauss-Jordan kernel
    (`ops/solve.py` — smaller systems pack more rows per VMEM tile, so
    the kernel gets FASTER per system as B shrinks), anything else the
    XLA Cholesky + two triangular solves.
    """
    if solver == "pallas":
        from ..ops.solve import cholesky_solve_batched

        return cholesky_solve_batched(
            A.astype(jnp.float32), b.astype(jnp.float32)
        )
    L = jax.lax.linalg.cholesky(A)
    y = jax.lax.linalg.triangular_solve(
        L, b[..., None], left_side=True, lower=True
    )
    return jax.lax.linalg.triangular_solve(
        L, y, left_side=True, lower=True, transpose_a=True
    )[..., 0]


def _half_iteration_impl(
    upd: jax.Array,        # [N, R] factor table being solved (donated)
    opp: jax.Array,        # [M, R] opposite-side factor table
    c_sorted: jax.Array,   # [nnz] int32
    v_sorted: jax.Array,   # [nnz] f32
    bucket_args: tuple,    # tuple of (rows, starts, counts) per bucket
    lam: jax.Array,        # traced scalar: sweeping λ must not recompile
    alpha: jax.Array,      # traced scalar
    *,
    ks: tuple,             # static: pad width per bucket
    implicit: bool,
    weighted_lambda: bool,
    precision: str,
    solver: str,
    gather_dtype: str = "float32",
    gather_mode: str = "row",
    solver_mode: str = "full",
    subspace_size: int = 0,
    fused_gather: str = "taa",
) -> jax.Array:
    def write(acc, rows, x):
        acc = upd if acc is None else acc
        # batch-padding rows carry row id >= N -> dropped by the scatter
        return acc.at[rows].set(
            x.astype(acc.dtype), mode="drop", unique_indices=True
        )

    out = _solve_buckets(
        write, opp, c_sorted, v_sorted, bucket_args, lam, alpha,
        ks=ks, implicit=implicit, weighted_lambda=weighted_lambda,
        precision=precision, solver=solver, gather_dtype=gather_dtype,
        gather_mode=gather_mode, solver_mode=solver_mode,
        subspace_size=subspace_size, fused_gather=fused_gather,
        upd_table=upd,
    )
    return upd if out is None else out


# jitted entry point; the impl stays reachable for vmapped λ sweeps
# (sweep_train_als), where the batching transform must see the raw fn.
# xray.instrument feeds the recompile detector: a λ sweep reuses the
# executable (traced scalar -> same signature) while a bucket-layout or
# rank change shows up as a signature delta on /debug/xray.
_half_iteration = xray.instrument("als.half_iteration")(
    functools.partial(
        jax.jit,
        static_argnames=(
            "ks", "implicit", "weighted_lambda", "precision", "solver",
            "gather_dtype", "gather_mode", "solver_mode", "subspace_size",
            "fused_gather",
        ),
        donate_argnums=(0,),
    )(_half_iteration_impl)
)


@xray.instrument("als.phase_probe")
@functools.partial(
    jax.jit,
    static_argnames=(
        "ks", "implicit", "weighted_lambda", "precision", "solver",
        "gather_dtype", "gather_mode", "solver_mode", "subspace_size",
        "fused_gather", "stop_after",
    ),
)
def _half_phase_probe(upd, opp, c_sorted, v_sorted, bucket_args, lam,
                      alpha, *, ks, implicit, weighted_lambda, precision,
                      solver, gather_dtype="float32", gather_mode="row",
                      solver_mode="full", subspace_size=0,
                      fused_gather="taa", stop_after="gather"):
    """Truncated half-iteration for pio-obs phase tracing: the same
    kernel prefix ``tools/breakdown_matrix.py`` probes (gather only /
    gather+Gram), jitted WITHOUT donation — the real, donating half
    still consumes ``upd`` right after the probes run."""
    return _solve_buckets(
        None, opp, c_sorted, v_sorted, bucket_args, lam, alpha,
        ks=ks, implicit=implicit, weighted_lambda=weighted_lambda,
        precision=precision, solver=solver, gather_dtype=gather_dtype,
        gather_mode=gather_mode, solver_mode=solver_mode,
        subspace_size=subspace_size, fused_gather=fused_gather,
        upd_table=upd, stop_after=stop_after,
    )


def _als_phase_trace_enabled() -> bool:
    """``PIO_TPU_TRACE_ALS=1`` arms per-phase span recording.  Opt-in
    because honest phase timing needs a fence per probe and per half —
    the async dispatch pipelining ``run()`` normally rides is exactly
    what the fences suspend (same trade bench.py makes)."""
    import os

    return os.environ.get("PIO_TPU_TRACE_ALS") == "1"


def _solve_buckets(
    upd_write,             # callback(rows, x) -> new upd table/shard
    opp: jax.Array,        # [M, R] full opposite table (local or gathered)
    c_sorted: jax.Array,
    v_sorted: jax.Array,
    bucket_args: tuple,
    lam: jax.Array,
    alpha: jax.Array,
    *,
    ks: tuple,
    implicit: bool,
    weighted_lambda: bool,
    precision: str,
    solver: str,
    gather_dtype: str = "float32",
    gather_mode: str = "row",
    solver_mode: str = "full",
    subspace_size: int = 0,
    fused_gather: str = "taa",
    upd_table: Optional[jax.Array] = None,
    gram: Optional[jax.Array] = None,
    stop_after: Optional[str] = None,
):
    """Shared bucket-solve math for the replicated and sharded paths
    (and the pio-live fold-in: `live/foldin.py` routes its
    fixed-capacity single-bucket row solves through this same function
    with a write callback that returns the solved block, so online
    fold-in and offline training can never drift apart numerically).

    ``solver_mode="subspace"`` (iALS++, arXiv 2110.14044) replaces the
    per-row full R×R normal-equation solve with a sweep over rank
    blocks of width ``subspace_size``: per block S, a Newton step on
    the block coordinates — exact because the objective is quadratic —

        H_S δ = -(g_S),  x_S ← x_S + δ

    where ``H_S`` is the B×B principal Gram submatrix (+ reg) and
    ``g_S`` the block gradient evaluated against an incrementally
    maintained per-row residual (explicit) / prediction + YtY·x caches
    (implicit).  Per sweep the Gram contraction drops from O(K·R²) to
    O(K·B·R) and the solves from O(R³) to O(R·B²).  The sweep warm-
    starts from the CURRENT factor row, read from ``upd_table`` (the
    full — possibly all-gathered — table being updated; required for
    subspace mode).  ``subspace_size >= R`` takes the full-solve branch
    below verbatim, so the degenerate config is bitwise-identical to
    ``solver_mode="full"``.

    ``stop_after`` ("gather" | "gram") truncates the per-bucket pipeline
    and returns a scalar reduction instead of writing factors — used by
    ``bench.py --phase-probe`` to attribute per-iteration cost to
    gather vs MXU vs solver against the REAL kernel (no drift-prone
    copy of this math in the bench).

    ``gram`` (implicit mode only) lets the sharded path supply the YtY
    matrix computed shard-locally + psum'd instead of redundantly from the
    gathered full table.

    ``gather_dtype="bfloat16"`` casts the opposite table once per
    half-iteration and feeds the MXU bf16 operands with f32 accumulation
    (``preferred_element_type``): the hot [B, K, R] gather moves half the
    HBM bytes.  The YtY gram, regularization, and solves stay f32.

    ``solver="fused"`` routes buckets through the single-pass Pallas
    kernel (`ops/fused_als.py`: in-kernel gather+Gram+regularize+
    Gauss-Jordan, ~12 B/rating of HBM traffic), using the RESOLVED
    ``fused_gather`` impl ("taa" take_along_axis sub-gathers or "dma"
    scalar-prefetched row copies — `ALSConfig.fused_gather`, resolved
    by `_resolve_solver` before any trace).  Under "taa", VMEM-fitting
    opposite tables stay resident and bigger ones STREAM through the
    kernel's third grid axis in id-range-masked chunks; under "dma"
    the table stays in HBM and rows arrive by async copy.  Only shapes
    with no tile plan at all (`fused_tile_plan` None: pathological
    chunk/sub-gather counts or a tiny VMEM/SMEM budget) keep the XLA
    path below.
    """
    r = opp.shape[-1]
    nnz = c_sorted.shape[0]
    # B >= R degenerates to the full-solve branch VERBATIM (bitwise-
    # identical compiled program), per the ALSConfig contract
    sub = solver_mode == "subspace" and 0 < subspace_size < r
    if sub and upd_table is None:
        raise ValueError(
            "solver_mode='subspace' requires the current factor table "
            "(upd_table) to warm-start the block sweep"
        )
    prec = jax.lax.Precision(
        {"highest": "highest", "high": "high", "default": "default"}[precision]
    )
    if implicit and gram is None:
        gram = jnp.einsum("mr,ms->rs", opp, opp, precision=prec)
    opp_g = (
        opp.astype(jnp.bfloat16)
        if gather_dtype == "bfloat16" and opp.dtype != jnp.bfloat16
        else opp
    )
    f32 = jnp.float32
    opp_grp = grp = None
    if gather_mode == "grouped":
        # tile-aligned slab gather (ALSConfig.gather_mode): group height
        # = the dtype's memory-tile sublane count (8 f32 / 16 bf16).
        # The slab table is the 3D view [M/G, G, R] — the SAME row-major
        # bytes, but XLA tiles the trailing (G, R) dims, so one gathered
        # [G, R] slice is whole (8,128)/(16,128) tiles.  (The 2D
        # [M/G, G*R] form would lay the G rows along LANES: a slab row
        # is then 1 sublane tall and every gather still pays the full
        # tile-height waste it was meant to eliminate.)
        grp = 8 * (4 // opp_g.dtype.itemsize)
        mg = -(-opp_g.shape[0] // grp) * grp
        opp_grp = jnp.pad(
            opp_g, ((0, mg - opp_g.shape[0]), (0, 0))
        ).reshape(mg // grp, grp, r)
    fused_side = False
    if solver == "fused" and stop_after is None and ks:
        from ..ops.fused_als import fused_side_fits

        fused_side = fused_side_fits(
            opp_g.shape[0], r, max(ks), opp_g.dtype.itemsize,
            fused_gather,
        )
    out = None
    for (rows, starts, counts), k in zip(bucket_args, ks):
        iota = jnp.arange(k, dtype=jnp.int32)
        pos = jnp.minimum(starts[:, None] + iota[None, :], nnz - 1)
        valid = iota[None, :] < counts[:, None]          # [B, K]
        idx = jnp.where(valid, c_sorted[pos], 0)
        val = jnp.where(valid, v_sorted[pos], 0.0)       # f32, masked
        maskf = valid.astype(f32)
        if fused_side:
            from ..ops.fused_als import fused_gather_gram_solve

            n_row = counts.astype(f32)
            lam_t = lam.astype(f32)
            if implicit:
                cwk = alpha.astype(f32) * val * maskf
                bwk = (1.0 + cwk) * maskf
                g0 = gram
            else:
                cwk = maskf
                bwk = val * maskf
                g0 = None
            if weighted_lambda:
                reg = lam_t * jnp.maximum(n_row, 1.0)
            else:
                reg = jnp.broadcast_to(lam_t, n_row.shape)
            x = fused_gather_gram_solve(
                opp_g, idx, cwk, bwk, reg, g0, precision=prec,
                gather_impl=fused_gather,
            )
            out = upd_write(out, rows, x)
            continue
        if opp_grp is not None:
            # slab gather + in-slab select: exact same rows as the row
            # gather, but every HBM read is a full memory tile.  The
            # [*, K, G, R] slab is G times the row gather's output, so
            # it's produced in row-chunks bounded by _GROUPED_SLAB_BYTES
            # — the select shrinks each chunk back to [*, K, R] before
            # the next one materializes.
            bsz, k_ = idx.shape
            per_row = k_ * grp * r * opp_grp.dtype.itemsize
            bc = max(1, min(bsz, _GROUPED_SLAB_BYTES // max(per_row, 1)))

            def _slab_rows(ix):
                rows_n = ix.shape[0]
                slab = jnp.take(opp_grp, ix // grp, axis=0)  # [n,K,G,R]
                sel = jnp.broadcast_to(
                    (ix % grp)[..., None, None], (rows_n, k_, 1, r)
                )
                return jnp.take_along_axis(slab, sel, axis=2)[..., 0, :]

            if bc >= bsz:
                Vm = _slab_rows(idx)
            else:
                Vm = jnp.concatenate(
                    [
                        _slab_rows(idx[lo : lo + bc])
                        for lo in range(0, bsz, bc)
                    ],
                    axis=0,
                )
            Vm = Vm * valid[..., None].astype(Vm.dtype)
        else:
            Vm = opp_g[idx] * valid[..., None].astype(opp_g.dtype)  # [B,K,R]
        if stop_after == "gather":
            out = (0.0 if out is None else out) + Vm.astype(f32).sum()
            continue
        n_row = counts.astype(f32)                       # [B]
        lam_t = lam.astype(f32)
        if weighted_lambda:
            reg = lam_t * jnp.maximum(n_row, 1.0)        # ALS-WR: λ·n_row
        else:
            reg = jnp.broadcast_to(lam_t, n_row.shape)
        if sub:
            # iALS++ block sweep: warm-start from the current factor
            # rows (batch-padding ids are OOB -> fill 0; their output
            # is dropped by the scatter anyway)
            x0 = upd_table.at[rows].get(
                mode="fill", fill_value=0.0
            ).astype(f32)
            cw_b = (alpha.astype(f32) * val * maskf) if implicit else None
            res = _subspace_sweep(
                Vm, val, maskf, x0, reg, cw_b, gram, prec, solver,
                subspace_size, gram_probe=stop_after == "gram",
            )
            if stop_after == "gram":
                out = (0.0 if out is None else out) + res
            else:
                out = upd_write(out, rows, res)
            continue
        # weight vectors are computed in f32 then cast to the gather dtype
        # right before the einsum, so a mixed-dtype contraction never
        # silently promotes (and re-materializes) the big Vm operand
        if implicit:
            cw = alpha.astype(f32) * val * maskf         # (c - 1), f32
            A = gram + jnp.einsum(
                "bk,bkr,bks->brs", cw.astype(Vm.dtype), Vm, Vm,
                precision=prec, preferred_element_type=f32,
            )
            b = jnp.einsum(
                "bk,bkr->br", ((1.0 + cw) * maskf).astype(Vm.dtype), Vm,
                precision=prec, preferred_element_type=f32,
            )
        else:
            A = jnp.einsum("bkr,bks->brs", Vm, Vm, precision=prec,
                           preferred_element_type=f32)
            b = jnp.einsum(
                "bk,bkr->br", (val * maskf).astype(Vm.dtype), Vm,
                precision=prec, preferred_element_type=f32,
            )
        A = A + reg[:, None, None] * jnp.eye(r, dtype=A.dtype)
        if stop_after == "gram":
            out = (0.0 if out is None else out) + A.sum() + b.sum()
            continue
        x = _spd_solve(A, b, solver)
        out = upd_write(out, rows, x)
    return out


def _subspace_sweep(
    Vm: jax.Array,          # [B, K, R] gathered+masked opposite rows
    val: jax.Array,         # [B, K] masked ratings, f32
    maskf: jax.Array,       # [B, K] validity mask, f32
    x0: jax.Array,          # [B, R] current factor rows, f32
    reg: jax.Array,         # [B] per-row regularization (λ or λ·n_row)
    cw: Optional[jax.Array],  # [B, K] implicit (c-1) weights, or None
    gram: Optional[jax.Array],  # [R, R] YtY (implicit mode), f32
    prec,
    solver: str,
    block: int,
    *,
    gram_probe: bool = False,
):
    """One iALS++ rank-block sweep over a bucket's rows (arXiv
    2110.14044 Alg. 2, batched over rows).

    Each block update is an exact Newton step on the block coordinates
    of the quadratic per-row objective, against caches maintained
    incrementally with rank-B work:

    * explicit — residual ``e = Vm·x - val`` ([B, K]); block gradient
      ``g_S = VsᵀE + reg·x_S``, Hessian ``H_S = VsᵀVs + reg·I``.
    * implicit — prediction ``p = Vm·x`` and ``q = x·YtY`` ([B, R]);
      ``g_S = q_S + Vsᵀ((c-1)p - c) + reg·x_S``,
      ``H_S = YtY[S,S] + Vsᵀdiag(c-1)Vs + reg·I``.

    ``gram_probe=True`` computes every block's (H, g) without solving
    or updating the caches and returns their scalar sum — the
    ``stop_after="gram"`` hook that keeps the per-phase timing probe
    honest for this mode (the Gram-contraction cost of a sweep, minus
    the solve/update half).
    """
    f32 = jnp.float32
    r = Vm.shape[-1]
    pred = jnp.einsum(
        "bkr,br->bk", Vm, x0.astype(Vm.dtype),
        precision=prec, preferred_element_type=f32,
    )
    e = q = None
    if cw is None:
        e = pred - val
    else:
        q = jnp.einsum("bs,sr->br", x0, gram, precision=prec)
    acc = jnp.zeros((), f32)
    for s in range(0, r, block):
        w = min(block, r - s)
        Vs = jax.lax.slice_in_dim(Vm, s, s + w, axis=2)   # [B, K, w]
        xs = jax.lax.slice_in_dim(x0, s, s + w, axis=1)   # [B, w]
        if cw is None:
            H = jnp.einsum("bks,bkt->bst", Vs, Vs, precision=prec,
                           preferred_element_type=f32)
            g = jnp.einsum("bk,bks->bs", e.astype(Vs.dtype), Vs,
                           precision=prec, preferred_element_type=f32)
        else:
            H = gram[s:s + w, s:s + w] + jnp.einsum(
                "bk,bks,bkt->bst", cw.astype(Vs.dtype), Vs, Vs,
                precision=prec, preferred_element_type=f32,
            )
            # (c-1)·p - c on rated items: cw is masked, so c·mask is
            # maskf + cw
            coef = cw * pred - maskf - cw
            g = q[:, s:s + w] + jnp.einsum(
                "bk,bks->bs", coef.astype(Vs.dtype), Vs,
                precision=prec, preferred_element_type=f32,
            )
        H = H + reg[:, None, None] * jnp.eye(w, dtype=H.dtype)
        g = g + reg[:, None] * xs
        if gram_probe:
            acc = acc + H.sum() + g.sum()
            continue
        d = -_spd_solve(H, g, solver)                    # [B, w]
        x0 = jax.lax.dynamic_update_slice_in_dim(x0, xs + d, s, axis=1)
        dp = jnp.einsum("bks,bs->bk", Vs, d.astype(Vs.dtype),
                        precision=prec, preferred_element_type=f32)
        if cw is None:
            e = e + dp
        else:
            pred = pred + dp
            q = q + jnp.einsum("bs,sr->br", d, gram[s:s + w, :],
                               precision=prec)
    return acc if gram_probe else x0


def build_sharded_half(
    mesh: Mesh,
    *,
    ks: tuple,
    implicit: bool,
    weighted_lambda: bool,
    precision: str,
    solver: str,
    gather_dtype: str = "float32",
    gather_mode: str = "row",
    solver_mode: str = "full",
    subspace_size: int = 0,
    fused_gather: str = "taa",
    coded: bool = False,
):
    """ALX-style half-iteration over block-sharded factor tables.

    Layout (SURVEY §2.7(2); the TPU answer to MLlib's block-partitioned
    ALS, reference `examples/scala-parallel-similarproduct/multi/src/main/
    scala/ALSAlgorithm.scala`):

    * Both factor tables live **sharded** ``P('data', None)`` at rest, so
      model capacity scales with total mesh HBM instead of one chip's.
    * Per half-iteration, each device all-gathers the opposite table over
      ICI (transient), solves its shard of every bucket's batch, then
      all-gathers the small solved blocks ``[B, R]`` and writes only the
      rows its own factor shard owns — updates never cross devices.
    * Rating COO arrays are SHARDED ``P('data')``: each device holds only
      the slices of the bucket rows it solves, in shard-local order with
      shard-local starts (``_plan_shard_layout``) — rating capacity
      scales with mesh HBM like MLlib's co-partitioned rating blocks,
      and the int32-offset ceiling applies per shard.

    ``coded=True`` (coded-ALS, arXiv 2105.03631; `parallel/coded.py`)
    builds the straggler-tolerant variant.  Signature grows two inputs
    and one output::

        fn(upd, opp, opp_parity, ok_mask, c, v, lam, alpha, *buckets)
          -> (new_upd, new_upd_parity)

    ``opp_parity`` is the replicated ``[M/d, R]`` f32 block sum of the
    opposite table; ``ok_mask`` a replicated ``[d]`` 0/1 vector.  A
    masked (late/dead) opposite block is reconstructed in-program as
    ``parity - sum(alive blocks)`` — exact while parity is current —
    and the masked shard's OWN rows are frozen at their previous values
    (the dead worker wrote nothing this half).  The returned parity is
    the block sum of the UPDATED table, so the next half's opposite
    parity is already fresh.  With an all-ones mask the math reduces to
    the plain path (reconstruction multiplies by zero).

    Requires row counts padded to a multiple of the mesh size; bucket
    padding rows carry ids >= the padded row count, so they drop out of
    every shard's scatter window.
    """
    import functools as _ft
    import inspect

    raw = getattr(jax, "shard_map", None)
    if raw is None:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map as raw
    # the replication-check kwarg was renamed check_rep -> check_vma; probe
    # the signature rather than the jax version
    params = inspect.signature(raw).parameters
    flag = "check_vma" if "check_vma" in params else "check_rep"
    shard_map = _ft.partial(raw, **{flag: False})

    axis = DATA_AXIS
    d = mesh.shape[axis]
    f32 = jnp.float32

    def solve_core(upd, opp_full, gram, c_sorted, v_sorted, lam, alpha,
                   flat_buckets):
        me = jax.lax.axis_index(axis)
        shard_n = upd.shape[0]
        lo = (me * shard_n).astype(jnp.int32)
        bucket_args = tuple(
            tuple(flat_buckets[i : i + 3])
            for i in range(0, len(flat_buckets), 3)
        )
        # subspace mode warm-starts each row's block sweep from the
        # CURRENT factor value, but this device solves rows owned by
        # OTHER shards — gather the full updating table transiently
        # too (f32: it is the iterate, not a bandwidth-discountable
        # operand).  One extra [N, R] all-gather per half-iteration;
        # stays zero-cost when the mode is off or degenerate.
        upd_full = None
        if solver_mode == "subspace" and 0 < subspace_size < upd.shape[-1]:
            upd_full = jax.lax.all_gather(upd, axis, axis=0, tiled=True)

        def write(acc, rows, x):
            acc = upd if acc is None else acc
            xg = jax.lax.all_gather(x, axis, axis=0, tiled=True)   # [B, R]
            rg = jax.lax.all_gather(rows, axis, axis=0, tiled=True)
            local = rg - lo
            inside = (local >= 0) & (local < shard_n)
            # OOB sentinel: shard_n is out of range -> dropped by the
            # scatter (covers other shards' rows AND bucket padding)
            safe = jnp.where(inside, local, shard_n)
            return acc.at[safe].set(xg.astype(acc.dtype), mode="drop")

        out = _solve_buckets(
            write, opp_full, c_sorted, v_sorted, bucket_args, lam, alpha,
            ks=ks, implicit=implicit, weighted_lambda=weighted_lambda,
            precision=precision, solver=solver,
            gather_dtype=gather_dtype, gather_mode=gather_mode,
            solver_mode=solver_mode, subspace_size=subspace_size,
            fused_gather=fused_gather, upd_table=upd_full, gram=gram,
        )
        return upd if out is None else out

    def _prec():
        return jax.lax.Precision(
            {"highest": "highest", "high": "high", "default": "default"}[
                precision
            ]
        )

    P_ = P
    sharded2 = P_(axis, None)
    rep = P_()
    bucket_specs = (P_(axis),) * (3 * len(ks))

    if not coded:

        def body(upd, opp, c_sorted, v_sorted, lam, alpha, *flat_buckets):
            # upd/opp arrive as local shards [Np/d, R] / [Mp/d, R]
            # cast BEFORE the all-gather so bf16 mode also halves ICI
            # traffic
            opp_send = (
                opp.astype(jnp.bfloat16)
                if gather_dtype == "bfloat16"
                else opp
            )
            opp_full = jax.lax.all_gather(
                opp_send, axis, axis=0, tiled=True
            )
            gram = None
            if implicit:
                # YtY from the LOCAL shard + psum: identical [R, R]
                # result at 1/d the FLOPs of redoing the full einsum on
                # every device
                gram = jax.lax.psum(
                    jnp.einsum("mr,ms->rs", opp, opp, precision=_prec()),
                    axis,
                )
            return solve_core(
                upd, opp_full, gram, c_sorted, v_sorted, lam, alpha,
                flat_buckets,
            )

        in_specs = (
            sharded2, sharded2, P_(axis), P_(axis), rep, rep,
        ) + bucket_specs
        mapped = shard_map(
            body, mesh=mesh, in_specs=in_specs, out_specs=sharded2,
        )
        return xray.instrument("als.sharded_half")(
            jax.jit(mapped, donate_argnums=(0,))
        )

    def coded_body(upd, opp, opp_parity, ok, c_sorted, v_sorted, lam,
                   alpha, *flat_buckets):
        me = jax.lax.axis_index(axis)
        opp_send = (
            opp.astype(jnp.bfloat16)
            if gather_dtype == "bfloat16"
            else opp
        )
        # mask the late/dead shard's block out of the gather, then put
        # its reconstruction back: parity - sum(alive).  The alive sum
        # rides f32 (the iterate's dtype) so bf16 gather mode does not
        # erode the reconstruction; with all shards alive the recon
        # block multiplies by zero and the math is the plain gather.
        okm = ok[me]
        masked_send = opp_send * okm.astype(opp_send.dtype)
        gathered = jax.lax.all_gather(masked_send, axis, axis=0,
                                      tiled=True)
        alive_sum = jax.lax.psum(opp * okm.astype(opp.dtype), axis)
        recon = (opp_parity - alive_sum.astype(f32))
        blocks = gathered.reshape((d,) + opp.shape)
        okb = ok.reshape((d,) + (1,) * opp.ndim).astype(opp_send.dtype)
        opp_full = (
            blocks * okb
            + recon[None].astype(opp_send.dtype) * (1.0 - okb)
        ).reshape(gathered.shape)
        gram = None
        if implicit:
            # per-shard gram + psum would read the dead shard's data;
            # fold the reconstructed block in explicitly instead
            alive_gram = jax.lax.psum(
                jnp.einsum(
                    "mr,ms->rs", opp * okm.astype(opp.dtype),
                    opp * okm.astype(opp.dtype), precision=_prec(),
                ),
                axis,
            )
            gram = alive_gram + jnp.einsum(
                "mr,ms->rs", recon, recon, precision=_prec(),
            ) * (1.0 - jnp.min(ok))
        out = solve_core(
            upd, opp_full, gram, c_sorted, v_sorted, lam, alpha,
            flat_buckets,
        )
        # a degraded shard wrote nothing this half: freeze its rows
        okw = okm.astype(out.dtype)
        out = out * okw + upd.astype(out.dtype) * (1.0 - okw)
        # parity of the UPDATED table — the next half's opposite parity
        new_parity = jax.lax.psum(out.astype(f32), axis)
        return out, new_parity

    in_specs = (
        sharded2, sharded2, rep, rep, P_(axis), P_(axis), rep, rep,
    ) + bucket_specs
    mapped = shard_map(
        coded_body, mesh=mesh, in_specs=in_specs,
        out_specs=(sharded2, rep),
    )
    return xray.instrument("als.coded_half")(
        jax.jit(mapped, donate_argnums=(0,))
    )


def _resolve_solver(cfg: ALSConfig) -> tuple[str, Optional[str]]:
    """Compile-probe kernel-backed solvers; degrade to "xla" on failure.

    Returns ``(solver, fused_gather_resolved)``: ``"pallas"`` probes
    the Gauss-Jordan solve kernel at this rank; ``"fused"`` resolves
    the in-kernel gather form (``cfg.fused_gather``; ``"auto"`` walks
    the per-backend probe order) and probes the EXACT (shape, dtype,
    precision, gather-impl) kernel variant production would run.  A
    fused request that resolves to no runnable variant degrades to
    ``("xla", None)`` — the loud-degradation artifacts
    (``solver_requested``/``degraded``/``fused_gather_resolved``) make
    that visible in every bench record.  All probes cache per
    (backend, variant) so trainers after the first pay nothing.
    """
    if cfg.solver == "pallas":
        from ..ops.solve import pallas_solver_ok

        # probe the dimension the kernel will actually solve: subspace
        # mode dispatches B×B subsystems, not R×R (tail blocks are
        # narrower still — probing the widest block suffices)
        dim = cfg.rank
        if cfg.solver_mode == "subspace" and 0 < cfg.subspace_size < cfg.rank:
            dim = cfg.subspace_size
        if not pallas_solver_ok(dim):
            return "xla", None
    elif cfg.solver == "fused":
        from ..ops.fused_als import resolve_gather_impl

        tb = 2 if cfg.gather_dtype == "bfloat16" else 4
        # probe the exact kernel variant production will run: precision,
        # table dtype, and gather impl are all static args of the
        # pallas lowering, so probing any other variant validates a
        # different kernel
        impl = resolve_gather_impl(
            512, cfg.rank, tb, cfg.matmul_precision, cfg.fused_gather
        )
        if impl is None:
            return "xla", None
        return "fused", impl
    return cfg.solver, None


class ALSTrainer:
    """Staged ALS state: build once, iterate cheaply.

    Separates the one-time host preprocessing + device staging from the
    iteration loop so that serving-time retrains, benchmarks, and
    warm-started sweeps don't re-pay staging.
    """

    def __init__(
        self,
        ratings: Ratings | tuple[np.ndarray, np.ndarray, np.ndarray],
        n_users: Optional[int] = None,
        n_items: Optional[int] = None,
        cfg: ALSConfig = ALSConfig(),
        mesh: Optional[Mesh] = None,
        staging: str = "auto",
    ):
        if isinstance(ratings, Ratings):
            u, i, v = ratings.user_ix, ratings.item_ix, ratings.rating
            n_users = ratings.n_users
            n_items = ratings.n_items
        else:
            u, i, v = ratings
            assert n_users is not None and n_items is not None
        self.cfg = cfg
        self.mesh = mesh if (mesh is not None and mesh.size > 1) else None
        self.n_users = n_users
        self.n_items = n_items
        # resolve the solver once: kernel-backed solvers are
        # compile-probed and degrade to XLA with a warning if the kernel
        # doesn't lower on this backend (round 2: a Mosaic regression
        # was only caught on the real chip; a user's train must survive
        # the next one).  fused_gather is the RESOLVED in-kernel gather
        # form (None unless the fused kernel is live) — bench artifacts
        # record it as fused_gather_resolved
        self.solver, self.fused_gather = _resolve_solver(cfg)

        n_dev = self.mesh.size if self.mesh is not None else 1
        # sharded factor tables need a real mesh and row counts divisible
        # by it; single-device "sharded" degenerates to replicated
        self.sharded = (
            cfg.factor_placement == "sharded" and self.mesh is not None
        )
        # single-device "sharded" degenerates to replicated, and coded
        # parity with it (there is no ring to straggle)
        self.coded = False
        self._pad_users = pad_to_multiple(n_users, n_dev)
        self._pad_items = pad_to_multiple(n_items, n_dev)
        nu = self._pad_users if self.sharded else n_users
        ni = self._pad_items if self.sharded else n_items
        if staging not in ("auto", "host", "device"):
            raise ValueError(
                f"staging must be 'auto', 'host' or 'device', got {staging!r}"
            )
        if self.sharded:
            # sharded placement stages a SHARDED COO: each device holds
            # only the rating slices of the bucket rows it solves
            # (_plan_shard_layout), so nnz capacity scales with mesh HBM
            # and the int32-offset ceiling applies per shard, not
            # globally.  device_put with a P('data') sharding moves each
            # byte to exactly one device — there is no replicated copy
            # to avoid, so the staging knob is moot here.
            if staging != "auto":
                logger.warning(
                    "staging=%r is ignored under factor_placement="
                    "'sharded': the sharded-COO layout has its own "
                    "staging path (trainer.staging == 'sharded')",
                    staging,
                )
            self.staging = "sharded"
            self._user_side = self._stage_side_sharded(
                build_bucket_layout(
                    u, i, v, nu, cfg.min_bucket_k,
                    cfg.max_ratings_per_row, batch_multiple=n_dev,
                    starts_dtype=np.int64,
                ),
                n_dev,
            )
            self._item_side = self._stage_side_sharded(
                build_bucket_layout(
                    i, u, v, ni, cfg.min_bucket_k,
                    cfg.max_ratings_per_row, batch_multiple=n_dev,
                    starts_dtype=np.int64,
                ),
                n_dev,
            )
        elif staging == "auto":
            # device staging pays an extra device program (one argsort +
            # repeat/gathers); worth it once the sorted-COO transfer
            # dwarfs that (big datasets), not for the small problems
            # tests and templates mostly train
            staging = "device" if len(v) >= 2_000_000 else "host"
        if not self.sharded:
            self.staging = staging
            if staging == "device":
                sides = self._stage_device(u, i, v, nu, ni, n_dev)
                self._user_side, self._item_side = sides
            else:
                self._user_side = self._stage(
                    build_bucket_layout(
                        u, i, v, nu, cfg.min_bucket_k,
                        cfg.max_ratings_per_row, batch_multiple=n_dev,
                    )
                )
                self._item_side = self._stage(
                    build_bucket_layout(
                        i, u, v, ni, cfg.min_bucket_k,
                        cfg.max_ratings_per_row, batch_multiple=n_dev,
                    )
                )
        if self.sharded:
            self._build_sharded_halves()
        self._init_loss(u, i, v)

    # per-sweep loss sample cap: the watchdog needs a *trajectory*, not
    # the exact training RMSE, so the loss pass runs over a fixed
    # seeded subsample of at most this many triples — its cost is
    # BOUNDED regardless of dataset scale (a full-COO pass was a
    # measured ~11% tax on the scale-0.02 CPU train; 64Ki samples put
    # the same trajectory at ~2%).  nnz <= the cap means the loss is
    # the exact training RMSE.
    LOSS_SAMPLE_MAX = 1 << 16

    def _init_loss(self, u, i, v) -> None:
        """Stage the (sub)sampled COO triples for the per-sweep loss
        pass (pio-tower).  ``cfg.loss_every`` None = auto: every sweep
        (the sample cap keeps it cheap at any scale)."""
        every = self.cfg.loss_every
        if every is None:
            every = 1
        self.loss_every = every
        if not every or len(v) == 0:
            self._loss_coo = None
            self.loss_sample_n = 0
        elif len(v) > self.LOSS_SAMPLE_MAX:
            pick = np.random.default_rng(self.cfg.seed).choice(
                len(v), size=self.LOSS_SAMPLE_MAX, replace=False,
            )
            pick.sort()
            self._loss_coo = (
                np.ascontiguousarray(np.asarray(u)[pick]),
                np.ascontiguousarray(np.asarray(i)[pick]),
                np.ascontiguousarray(
                    np.asarray(v)[pick].astype(np.float32)),
            )
            self.loss_sample_n = int(self.LOSS_SAMPLE_MAX)
        else:
            self._loss_coo = (
                np.asarray(u), np.asarray(i),
                np.asarray(v).astype(np.float32),
            )
            self.loss_sample_n = int(len(v))
        self._loss_dev = None  # device copies, staged on first use

    def sweep_loss(self, U, V) -> Optional[float]:
        """Per-sweep training RMSE over the retained (sub)sampled COO
        (``_sq_err_sum`` — same math as :func:`rmse`; exact when the
        dataset fits :attr:`LOSS_SAMPLE_MAX`).  The sample is staged to
        the device ONCE and reused every sweep."""
        if self._loss_coo is None:
            return None
        if self._loss_dev is None:
            u, i, v = self._loss_coo
            if self.mesh is not None:
                put = lambda x: jax.device_put(  # noqa: E731
                    x, replicated(self.mesh))
            else:
                put = jnp.asarray
            self._loss_dev = (put(u), put(i), put(v))
        ud, idv, vd = self._loss_dev
        n = self.loss_sample_n
        return math.sqrt(float(_sq_err_sum(U, V, ud, idv, vd)) / n)

    def _build_sharded_halves(self) -> None:
        cfg = self.cfg
        self.coded = bool(cfg.coded_shards) and self.sharded
        common = dict(
            implicit=cfg.implicit,
            weighted_lambda=cfg.weighted_lambda,
            precision=cfg.matmul_precision,
            solver=self.solver,
            gather_dtype=cfg.gather_dtype,
            gather_mode=cfg.gather_mode,
            solver_mode=cfg.solver_mode,
            subspace_size=cfg.subspace_size,
            fused_gather=self.fused_gather or "taa",
            coded=self.coded,
        )
        self._sharded_user_half = build_sharded_half(
            self.mesh, ks=self._user_side["ks"], **common
        )
        self._sharded_item_half = build_sharded_half(
            self.mesh, ks=self._item_side["ks"], **common
        )
        if self.coded:
            from ..parallel.coded import ShardHealth, build_parity_fn

            self._parity_fn = build_parity_fn(self.mesh)
            self._parity_state: dict[str, jax.Array] = {}
            # one health tracker per trainer: a worker_kill is sticky
            # across run() calls, the way a dead host stays dead
            self.shard_health = ShardHealth(
                self.mesh.size,
                hop_budget_s=cfg.shard_hop_budget_s or None,
                op="als.half",
            )

    @classmethod
    def distributed(
        cls,
        local_ratings,
        n_users: Optional[int] = None,
        n_items: Optional[int] = None,
        cfg: ALSConfig = ALSConfig(factor_placement="sharded"),
        mesh: Optional[Mesh] = None,
        exchange_dir=None,
        tag: str = "als-coo",
        timeout: float = 120.0,
    ) -> "ALSTrainer":
        """Multi-host sharded-COO trainer.

        Each process contributes only its LOCAL rating triples (e.g. the
        entity-hash shard a `find_columnar_sharded` scan returned,
        encoded against the global id index); the triples are exchanged
        so every device receives exactly the slices of the bucket rows
        it solves (`parallel/ingest.exchange_ratings_by_owner`).  **No
        process ever materializes the full COO** — the round-2
        `gather_ratings` all-gather is gone from this path, completing
        the scaling story: factor tables shard over mesh HBM, the rating
        matrix shards over mesh HBM, and the host-side COO shards over
        cluster memory (the role HBase region-sharding played for the
        reference, `storage/hbase/HBPEvents.scala:99-105`).

        Only per-row COUNT vectors (a few hundred KB at ML-20M scale)
        are all-gathered, to give every process the identical global
        bucket plan.  Single-process (or no real mesh) falls back to the
        ordinary constructor.
        """
        import jax

        if isinstance(local_ratings, Ratings):
            u, i, v = (
                local_ratings.user_ix,
                local_ratings.item_ix,
                local_ratings.rating,
            )
            n_users = local_ratings.n_users
            n_items = local_ratings.n_items
        else:
            u, i, v = local_ratings
            assert n_users is not None and n_items is not None
        if jax.process_count() <= 1 or mesh is None or mesh.size <= 1:
            return cls((u, i, v), n_users, n_items, cfg, mesh=mesh)
        if cfg.factor_placement != "sharded":
            raise ValueError(
                "ALSTrainer.distributed requires "
                "factor_placement='sharded' (the sharded-COO layout)"
            )
        if exchange_dir is None:
            raise ValueError("exchange_dir is required for multi-process")

        from jax.experimental import multihost_utils

        self = cls.__new__(cls)
        self.cfg = cfg
        self.mesh = mesh
        self.n_users = n_users
        self.n_items = n_items
        self.solver, self.fused_gather = _resolve_solver(cfg)
        n_dev = mesh.size
        self.sharded = True
        self.staging = "sharded-distributed"
        self._pad_users = pad_to_multiple(n_users, n_dev)
        self._pad_items = pad_to_multiple(n_items, n_dev)

        def global_counts(rows, n_pad):
            local = np.bincount(rows, minlength=n_pad).astype(np.int64)
            return np.asarray(
                multihost_utils.process_allgather(local)
            ).reshape(jax.process_count(), n_pad).sum(axis=0)

        device_proc = np.asarray(
            [d.process_index for d in mesh.devices.reshape(-1)], np.int32
        )
        self._user_side = self._stage_side_distributed(
            u, i, v, global_counts(u, self._pad_users), self._pad_users,
            n_dev, device_proc, exchange_dir, f"{tag}-user", timeout,
        )
        self._item_side = self._stage_side_distributed(
            i, u, v, global_counts(i, self._pad_items), self._pad_items,
            n_dev, device_proc, exchange_dir, f"{tag}-item", timeout,
        )
        self._build_sharded_halves()
        # distributed staging holds only LOCAL triples; a global
        # training loss is not computable from one process
        self.loss_every = 0
        self._loss_coo = None
        self._loss_dev = None
        self.loss_sample_n = 0
        return self

    def _stage_side_distributed(
        self, rows, cols, vals, counts, n_rows_pad, n_dev, device_proc,
        exchange_dir, tag, timeout,
    ):
        """One side's sharded staging from process-LOCAL triples.

        Every process derives the identical global bucket/shard plan from
        the (all-gathered) count vector, exchanges its triples to each
        row's owning process, and builds shard arrays only for its own
        addressable devices — assembled into the global sharded array
        with ``make_array_from_single_device_arrays``.
        """
        import jax

        from ..parallel.ingest import exchange_ratings_by_owner

        cfg = self.cfg
        starts = np.concatenate(([0], np.cumsum(counts)[:-1]))
        buckets = _assemble_buckets(
            counts, starts, n_rows_pad, cfg.min_bucket_k,
            cfg.max_ratings_per_row, batch_multiple=n_dev,
            starts_dtype=np.int64,
        )
        _, local_starts, L = _plan_shard_layout(
            buckets, n_dev, build_perm=False
        )
        # row -> owning device / shard-local start / effective cap
        row_dev = np.zeros(n_rows_pad, np.int32)
        row_ls = np.zeros(n_rows_pad, np.int64)
        row_cap = np.zeros(n_rows_pad, np.int64)
        for b, ls in zip(buckets, local_starts):
            chunk = len(b.rows) // n_dev
            real = b.rows < n_rows_pad
            rr = b.rows[real]
            row_dev[rr] = (np.arange(len(b.rows))[real] // chunk).astype(
                np.int32
            )
            row_ls[rr] = ls[real]
            row_cap[rr] = b.counts[real]

        rows2, cols2, vals2 = exchange_ratings_by_owner(
            rows, cols, vals, device_proc[row_dev], exchange_dir, tag,
            timeout=timeout,
        )
        # deterministic within-row order (sources may interleave): sort
        # by (row, col); occurrence index beyond the per-row cap
        # (max_ratings_per_row) is dropped.  NOTE the retained subset
        # under a cap is deterministic but (row, col)-ordered, whereas a
        # single-process train keeps the first cap entries in scan
        # order — with hash-sharded multi-process scans no global scan
        # order exists to reproduce, so capped distributed trains are
        # reproducible against themselves, not bit-equal to a
        # single-process capped train
        order = np.lexsort((cols2, rows2))
        rows2, cols2, vals2 = rows2[order], cols2[order], vals2[order]
        rc = np.bincount(rows2, minlength=n_rows_pad).astype(np.int64)
        rstart = np.concatenate(([0], np.cumsum(rc)[:-1]))
        occ = np.arange(len(rows2), dtype=np.int64) - rstart[rows2]
        keep = occ < row_cap[rows2]
        rows2, cols2, vals2, occ = (
            rows2[keep], cols2[keep], vals2[keep], occ[keep]
        )
        slot = (row_ls[rows2] + occ).astype(np.int64)
        dev_of = row_dev[rows2]

        pid = jax.process_index()
        mesh_devs = list(self.mesh.devices.reshape(-1))
        sh = NamedSharding(self.mesh, P(DATA_AXIS))
        c_parts, v_parts = [], []
        for di, d in enumerate(mesh_devs):
            if d.process_index != pid:
                continue
            sel = dev_of == di
            c_loc = np.zeros(L, np.int32)
            v_loc = np.zeros(L, np.float32)
            c_loc[slot[sel]] = cols2[sel]
            v_loc[slot[sel]] = vals2[sel]
            c_parts.append(jax.device_put(c_loc, d))
            v_parts.append(jax.device_put(v_loc, d))
        c_g = jax.make_array_from_single_device_arrays(
            (n_dev * L,), sh, c_parts
        )
        v_g = jax.make_array_from_single_device_arrays(
            (n_dev * L,), sh, v_parts
        )
        from ..parallel.mesh import shard_put

        return {
            "c_sorted": c_g,
            "v_sorted": v_g,
            "shard_len": L,
            "ks": tuple(b.k for b in buckets),
            "buckets": tuple(
                (
                    shard_put(b.rows, self.mesh, P(DATA_AXIS)),
                    shard_put(ls, self.mesh, P(DATA_AXIS)),
                    shard_put(b.counts, self.mesh, P(DATA_AXIS)),
                )
                for b, ls in zip(buckets, local_starts)
            ),
        }

    def _stage_device(self, u, i, v, nu, ni, n_dev):
        """Compact-transfer staging: host counting-sort once, expand the
        opposite side **on device**.

        The host path transfers two full sorted copies of the COO
        (``[nnz]`` ids + values per side — 320 MB for ML-20M at f32/int32).
        Here the host counting-sorts the COO by user ONCE (O(n) native
        C++, `native/bucketize.cpp`; NumPy fallback) and only
        ``(item_ids, values)`` in transfer order cross the host↔device
        link, in the narrowest lossless dtypes (uint16 ids when the id
        space fits, uint8 half-star rating codes — ~60 MB for ML-20M,
        5x less than the host path, 2.3x less than round 3's raw-COO
        transfer): the user-id column is never transferred at all.  On
        device the user side's grouping IS the transfer order (zero
        work), user ids are reconstructed from the per-row counts
        (``repeat``), and the item side is one argsort + gathers.

        The TPU lesson generalizes: host↔device bytes are the scarce
        resource (PCIe, or worse a DCN/tunnel hop), device sort is cheap
        — and bytes you can DERIVE device-side are cheaper still.
        """
        if len(v) >= np.iinfo(np.int32).max:
            # same int32-offset ceiling as build_bucket_layout: starts and
            # gather positions would wrap
            raise ValueError(
                f"{len(v):,} ratings exceed the int32 offset range of a "
                "single bucket layout; shard the COO across hosts first"
            )
        # the host path's counting sort validates id ranges; match it here
        # BEFORE the uint16 compaction can wrap an oversized id silently
        if len(v):
            u = np.asarray(u)
            i = np.asarray(i)
            if int(u.min()) < 0 or int(u.max()) >= self.n_users:
                raise ValueError(
                    f"user ids must be in [0, {self.n_users}); "
                    f"got [{int(u.min())}, {int(u.max())}]"
                )
            if int(i.min()) < 0 or int(i.max()) >= self.n_items:
                raise ValueError(
                    f"item ids must be in [0, {self.n_items}); "
                    f"got [{int(i.min())}, {int(i.max())}]"
                )
        from ..native import sort_coo_by_row

        # one O(n) host counting sort by user; its counts/starts feed
        # the user-side bucket plan directly
        i_by_u, v_by_u, counts_u, starts_u = sort_coo_by_row(
            np.asarray(u, np.int32), np.asarray(i, np.int32),
            np.asarray(v, np.float32), nu,
        )
        counts_i = np.bincount(i, minlength=ni).astype(np.int32)
        starts_i = np.concatenate(
            ([0], np.cumsum(counts_i)[:-1])
        ).astype(np.int32)
        cfg = self.cfg
        buckets_u = _assemble_buckets(
            np.asarray(counts_u, np.int32), np.asarray(starts_u, np.int32),
            nu, cfg.min_bucket_k, cfg.max_ratings_per_row,
            batch_multiple=n_dev,
        )
        buckets_i = _assemble_buckets(
            counts_i, starts_i, ni, cfg.min_bucket_k,
            cfg.max_ratings_per_row, batch_multiple=n_dev,
        )

        def compact_ids(x, n):
            return x.astype(np.uint16) if n <= (1 << 16) else \
                np.ascontiguousarray(x, dtype=np.int32)

        twice = v_by_u * 2.0
        half_star = (
            v_by_u.size > 0
            and float(v_by_u.min(initial=0.0)) >= 0.0
            and float(v_by_u.max(initial=0.0)) <= 127.5
            and bool(np.all(twice == np.round(twice)))
        )
        v_enc = twice.astype(np.uint8) if half_star else v_by_u
        v_scale = 0.5 if half_star else 1.0

        if self.mesh is not None:
            put = lambda x: jax.device_put(x, replicated(self.mesh))  # noqa: E731
        else:
            put = jax.device_put
        i_enc = compact_ids(i_by_u, ni)
        counts_enc = np.asarray(counts_u, np.int32)
        # observability: the bytes this path actually moves host->device
        # (the claim the on-chip battery checks; buckets are ~KB noise)
        self.staged_transfer_bytes = (
            i_enc.nbytes + v_enc.nbytes + counts_enc.nbytes
        )
        i_dev = put(i_enc)
        v_dev = put(v_enc)
        counts_dev = put(counts_enc)
        scale = jnp.asarray(v_scale, jnp.float32)
        cs_u, vs_u, cs_i, vs_i = _device_expand_sides(
            i_dev, v_dev, counts_dev, scale
        )
        return (
            self._stage_side(cs_u, vs_u, buckets_u),
            self._stage_side(cs_i, vs_i, buckets_i),
        )

    def _stage(self, layout: BucketLayout):
        """Transfer the sorted COO + bucket index vectors to the device."""
        return self._stage_side(
            layout.col_sorted, layout.val_sorted, layout.buckets
        )

    def _stage_side(self, c_sorted, v_sorted, buckets):
        """Place one side's arrays; accepts host or already-device arrays."""
        if self.mesh is not None:
            rep = replicated(self.mesh)
            dp = NamedSharding(self.mesh, P(DATA_AXIS))
            put_rep = lambda x: jax.device_put(x, rep)  # noqa: E731
            put_dp = lambda x: jax.device_put(x, dp)    # noqa: E731
        else:
            put_rep = put_dp = jnp.asarray
        return {
            "c_sorted": put_rep(c_sorted),
            "v_sorted": put_rep(v_sorted),
            "ks": tuple(b.k for b in buckets),
            "buckets": tuple(
                (put_dp(b.rows), put_dp(b.starts), put_dp(b.counts))
                for b in buckets
            ),
        }

    def _stage_side_sharded(self, layout: BucketLayout, n_dev: int):
        """Place one side's COO SHARDED: device ``d`` receives only the
        rating slices of the bucket rows it solves, in shard-local order
        (``_plan_shard_layout``).  The per-bucket starts arrays are the
        shard-LOCAL offsets, so the device gather indexes its own shard.
        """
        perm, local_starts, L = _plan_shard_layout(layout.buckets, n_dev)
        flat = perm.reshape(-1)
        c_sh = np.ascontiguousarray(layout.col_sorted[flat])
        v_sh = np.ascontiguousarray(layout.val_sorted[flat])
        from ..parallel.mesh import shard_put

        # shard_put, not device_put: a caller may hand the full COO to
        # every process of a multi-process mesh (e.g. a sharded sweep
        # after a replicated-path read); device_put would reject the
        # non-addressable devices
        put_dp = lambda x: shard_put(x, self.mesh, P(DATA_AXIS))  # noqa: E731
        return {
            "c_sorted": put_dp(c_sh),
            "v_sorted": put_dp(v_sh),
            "shard_len": L,
            "ks": tuple(b.k for b in layout.buckets),
            "buckets": tuple(
                (put_dp(b.rows), put_dp(ls), put_dp(b.counts))
                for b, ls in zip(layout.buckets, local_starts)
            ),
        }

    @property
    def coo_shard_entries(self) -> Optional[int]:
        """Per-device padded rating-slot count of the sharded COO layout
        (the HBM-scaling observable: ~nnz/mesh_size + padding), or None
        when the COO is replicated.  Public accessor — demos and
        capacity planners should use this, not the staging internals."""
        if not self.sharded:
            return None
        return int(self._user_side["shard_len"])

    def init_factors(self) -> tuple[jax.Array, jax.Array]:
        """MLlib-style init: N(0, 1)/sqrt(rank), fixed seed.

        Sharded placement pads the row dim to the mesh size with ZERO rows
        (never solved; zeros keep the implicit-mode Gram matrix exact) and
        places each table ``P('data', None)``.
        """
        cfg = self.cfg
        key = jax.random.PRNGKey(cfg.seed)
        ku, ki = jax.random.split(key)
        dtype = jnp.dtype(cfg.compute_dtype)
        U = jax.random.normal(ku, (self.n_users, cfg.rank), dtype)
        U = U / jnp.sqrt(cfg.rank).astype(dtype)
        V = jax.random.normal(ki, (self.n_items, cfg.rank), dtype)
        V = V / jnp.sqrt(cfg.rank).astype(dtype)
        if self.sharded:
            from ..parallel.mesh import shard_put

            U = jnp.pad(U, ((0, self._pad_users - self.n_users), (0, 0)))
            V = jnp.pad(V, ((0, self._pad_items - self.n_items), (0, 0)))
            spec = P(DATA_AXIS, None)
            # shard_put handles meshes spanning processes (device_put
            # rejects non-addressable shardings)
            return (
                shard_put(np.asarray(U), self.mesh, spec),
                shard_put(np.asarray(V), self.mesh, spec),
            )
        if self.mesh is not None:
            U = jax.device_put(U, replicated(self.mesh))
            V = jax.device_put(V, replicated(self.mesh))
        return U, V

    def _coded_parity(self, name: str, table: jax.Array) -> jax.Array:
        """Replicated parity block of ``table``, cached under ``name``
        ("user"/"item"); refreshed by each coded half's returned parity
        and reset per :meth:`run` (fresh iterates mean fresh parity)."""
        p = self._parity_state.get(name)
        if p is None:
            p = self._parity_fn(table)
            self._parity_state[name] = p
        return p

    def _half(self, upd, opp, side, lam: Optional[float] = None) -> jax.Array:
        cfg = self.cfg
        lam_t = jnp.asarray(cfg.lam if lam is None else lam, jnp.float32)
        if self.sharded:
            fn = (
                self._sharded_user_half
                if side is self._user_side
                else self._sharded_item_half
            )
            flat = [a for b in side["buckets"] for a in b]
            if self.coded:
                upd_name = (
                    "user" if side is self._user_side else "item"
                )
                opp_name = "item" if upd_name == "user" else "user"
                # consult the dist.* fault points / hop budget BEFORE
                # dispatch: a late shard is served from parity, a
                # killed one stays frozen (parallel/coded.ShardHealth)
                ok = self.shard_health.poll()
                new, new_par = fn(
                    upd, opp,
                    self._coded_parity(opp_name, opp),
                    jnp.asarray(ok, jnp.float32),
                    side["c_sorted"], side["v_sorted"],
                    lam_t,
                    jnp.asarray(cfg.alpha, jnp.float32),
                    *flat,
                )
                self._parity_state[upd_name] = new_par
                return new
            return fn(
                upd, opp, side["c_sorted"], side["v_sorted"],
                lam_t,
                jnp.asarray(cfg.alpha, jnp.float32),
                *flat,
            )
        return _half_iteration(
            upd, opp, side["c_sorted"], side["v_sorted"], side["buckets"],
            lam_t,
            jnp.asarray(cfg.alpha, jnp.float32),
            ks=side["ks"],
            implicit=cfg.implicit,
            weighted_lambda=cfg.weighted_lambda,
            precision=cfg.matmul_precision,
            solver=self.solver,
            gather_dtype=cfg.gather_dtype,
            gather_mode=cfg.gather_mode,
            solver_mode=cfg.solver_mode,
            subspace_size=cfg.subspace_size,
            fused_gather=self.fused_gather or "taa",
        )

    def _traced_half(self, upd, opp, side, side_name: str, it: int,
                     lam: Optional[float],
                     collect: Optional[dict] = None) -> jax.Array:
        """One half-iteration with pio-obs phase spans (als.gather /
        als.gram / als.solve), attributed by the fence-probe subtraction
        idiom: time the gather-only truncation, the gather+Gram
        truncation, and the full half, each fenced; the deltas are the
        per-phase device times (ALX §5: per-phase timing is what makes
        TPU factorization tunable).  Sharded placement has no probe
        entry point — it records the fenced full half as ``als.half``.

        ``collect`` (pio-tower) accumulates the emitted phase times as
        side-qualified keys (``user.gather`` ...) for the run
        manifest's sweep record.
        """
        from ..obs import get_tracer

        tracer = get_tracer()
        attrs = {"side": side_name, "iteration": it}

        def timed(fn, warm: bool):
            if warm:
                fence(fn())  # compile outside the measured span
            t0 = time.perf_counter()
            out = fn()
            fence(out)
            return out, time.perf_counter() - t0

        def emit(phase: str, dt: float) -> None:
            tracer.record(phase, dt, attrs=attrs)
            TRAIN_PHASE_SECONDS.labels(phase=phase).observe(dt)
            if collect is not None:
                key = f"{side_name}.{phase.rsplit('.', 1)[-1]}"
                collect[key] = collect.get(key, 0.0) + dt

        if self.sharded:
            new, t_full = timed(
                lambda: self._half(upd, opp, side, lam=lam), warm=False
            )
            emit("als.half", t_full)
            return new

        cfg = self.cfg
        lam_t = jnp.asarray(cfg.lam if lam is None else lam, jnp.float32)
        alpha_t = jnp.asarray(cfg.alpha, jnp.float32)

        def probe(stop):
            return _half_phase_probe(
                upd, opp, side["c_sorted"], side["v_sorted"],
                side["buckets"], lam_t, alpha_t,
                ks=side["ks"], implicit=cfg.implicit,
                weighted_lambda=cfg.weighted_lambda,
                precision=cfg.matmul_precision, solver=self.solver,
                gather_dtype=cfg.gather_dtype,
                gather_mode=cfg.gather_mode,
                solver_mode=cfg.solver_mode,
                subspace_size=cfg.subspace_size,
                fused_gather=self.fused_gather or "taa",
                stop_after=stop,
            )

        # the probes must run BEFORE the real half: it donates ``upd``
        warm = it == 0
        _, t_gather = timed(lambda: probe("gather"), warm)
        _, t_gram_cum = timed(lambda: probe("gram"), warm)
        new, t_full = timed(
            lambda: self._half(upd, opp, side, lam=lam), warm=False
        )
        emit("als.gather", t_gather)
        emit("als.gram", max(t_gram_cum - t_gather, 0.0))
        emit("als.solve", max(t_full - t_gram_cum, 0.0))
        return new

    def run(
        self,
        U: jax.Array,
        V: jax.Array,
        num_iterations: int,
        lam: Optional[float] = None,
    ) -> tuple[jax.Array, jax.Array]:
        """Iterate; treats U/V functionally (the caller's arrays survive).

        The half-iterations donate their working buffers, so copy the
        inputs once up front — two [N, R] copies are noise next to one
        half-iteration, and callers keep usable arrays for warm restarts.

        ``lam`` overrides the config's regularization for THIS run: λ is
        a traced scalar, so sweeping it reuses the compiled executables
        and the staged (possibly sharded) COO — the sweep path for
        problems too big for the vmapped ``sweep_train_als``.
        """
        from ..resilience import faults

        U = jnp.array(U, copy=True)
        V = jnp.array(V, copy=True)
        if self.coded:
            # fresh iterates mean the cached parity blocks are stale:
            # recompute lazily from THESE tables on first use
            self._parity_state = {}
        trace_phases = _als_phase_trace_enabled()
        session = tower.active_session()
        if session is not None:
            # the workflow layer opened the session without knowing the
            # algorithm's iteration budget; declare it for the ETA
            session.set_sweeps_planned(self.cfg.num_iterations)
        for it in range(num_iterations):
            t_sweep = time.perf_counter()
            phases: dict[str, float] = {}
            if trace_phases:
                # half-iteration granularity (fence-probe subtraction):
                # opt-in via PIO_TPU_TRACE_ALS=1 — the probes re-run
                # truncated halves, overhead the always-on path refuses
                U = self._traced_half(U, V, self._user_side, "user", it,
                                      lam, collect=phases)
                V = self._traced_half(V, U, self._item_side, "item", it,
                                      lam, collect=phases)
            else:
                # always-on sweep telemetry: one fence per half gives
                # the user/item split with zero extra device work (the
                # halves are data-dependent, so the device pipeline
                # loses nothing; only host dispatch-ahead is traded)
                t0 = time.perf_counter()
                U = self._half(U, V, self._user_side, lam=lam)
                fence(U)
                phases["user_half"] = time.perf_counter() - t0
                t0 = time.perf_counter()
                V = self._half(V, U, self._item_side, lam=lam)
                fence(V)
                phases["item_half"] = time.perf_counter() - t0
                TRAIN_PHASE_SECONDS.labels(phase="als.user_half").observe(
                    phases["user_half"]
                )
                TRAIN_PHASE_SECONDS.labels(phase="als.item_half").observe(
                    phases["item_half"]
                )
            if faults.fired("train.nan"):
                # poison the iterates the way an exploding sweep would;
                # the convergence watchdog must catch it THIS sweep
                U = U * jnp.asarray(float("nan"), U.dtype)
            loss = None
            if self.loss_every and (it + 1) % self.loss_every == 0:
                t0 = time.perf_counter()
                loss = self.sweep_loss(U, V)
                if loss is not None:
                    phases["loss"] = time.perf_counter() - t0
            finite = True
            if session is not None and session.wants_finite_check():
                t0 = time.perf_counter()
                finite = bool(_finite_all(U, V))
                phases["check"] = time.perf_counter() - t0
            # may raise tower.ConvergenceError — the typed watchdog
            # abort propagates out of the training run with the
            # manifest already finalized
            tower.record_sweep(
                time.perf_counter() - t_sweep, phases,
                loss=loss, factors_finite=finite, source=id(self),
            )
            logger.debug("ALS iteration %d/%d complete", it + 1,
                         num_iterations)
        # fence, not block_until_ready: the latter is a no-op on some
        # remote-tunnel backends (parallel/mesh.py fence docstring), which
        # would make every caller's wall-clock a dispatch time
        fence(U, V)
        return U, V

    def train(
        self,
        checkpointer=None,
        checkpoint_every: int = 5,
        resume: bool = True,
    ) -> ALSFactors:
        """Full run; with a :class:`~predictionio_tpu.workflow.checkpoint.
        StepCheckpointer`, factor state is saved every ``checkpoint_every``
        iterations and a crashed run resumes from the latest step (the
        reference reruns failed training from scratch)."""
        if checkpointer is not None and checkpoint_every <= 0:
            raise ValueError("checkpoint_every must be positive")
        U, V = self.init_factors()
        if checkpointer is None:
            # one call keeps the 2*num_iterations dispatches async
            U, V = self.run(U, V, self.cfg.num_iterations)
            return self._factors(U, V)
        start = 0
        if resume:
            latest = checkpointer.latest_step()
            if latest is not None:
                state = checkpointer.restore(latest, like={"U": U, "V": V})
                U, V = state["U"], state["V"]
                start = latest
                logger.info("resuming ALS from iteration %d", start)
        it = start
        while it < self.cfg.num_iterations:
            chunk = min(checkpoint_every, self.cfg.num_iterations - it)
            U, V = self.run(U, V, chunk)
            it += chunk
            checkpointer.save(it, {"U": U, "V": V})
        return self._factors(U, V)

    def _factors(self, U, V) -> ALSFactors:
        """Host factor arrays; sharded runs drop the mesh-padding rows.

        On a multi-process mesh the trained tables span hosts; gather
        them to every process (the deploy path loads full tables — the
        reference's PAlgorithm models were likewise collected to the
        driver before persisting)."""

        def to_host(a):
            if hasattr(a, "is_fully_addressable") and not a.is_fully_addressable:
                from jax.experimental import multihost_utils

                return np.asarray(
                    multihost_utils.process_allgather(a, tiled=True)
                )
            return np.asarray(a)

        return ALSFactors(
            user_factors=to_host(U)[: self.n_users],
            item_factors=to_host(V)[: self.n_items],
        )


def train_als(
    ratings: Ratings | tuple[np.ndarray, np.ndarray, np.ndarray],
    n_users: Optional[int] = None,
    n_items: Optional[int] = None,
    cfg: ALSConfig = ALSConfig(),
    mesh: Optional[Mesh] = None,
) -> ALSFactors:
    """Run ALS to convergence budget; returns host factor arrays."""
    return ALSTrainer(ratings, n_users, n_items, cfg, mesh).train()


def sweep_train_als(
    ratings: Ratings | tuple[np.ndarray, np.ndarray, np.ndarray],
    n_users: Optional[int] = None,
    n_items: Optional[int] = None,
    cfg: ALSConfig = ALSConfig(),
    lams: Sequence[float] = (),
    mesh: Optional[Mesh] = None,
) -> list[ALSFactors]:
    """Train one model per λ candidate SIMULTANEOUSLY via ``vmap``.

    The TPU-native answer to the reference's parallel evaluation sweep
    (`MetricEvaluator.scala:183-192` scores candidates with a Scala
    parallel collection; SURVEY §2.7(4) calls for vmapped sweeps): all K
    candidates' half-iterations run as ONE batched XLA program — the
    gathers, Gram einsums, and solves get a free leading batch dim on the
    MXU, and staging/bucketing is paid once for the whole sweep instead
    of once per candidate (FastEval caches reads across candidates; this
    also fuses the compute).

    Memory scales ×K (factor tables and the per-bucket gathered blocks),
    so the VMAPPED form fits evaluation-scale problems, not the full
    ML-20M train.  The vmapped form needs replicated placement and the
    XLA solver (Pallas grids don't batch under vmap); **sharded
    placement sweeps sequentially instead** — staging and the compiled
    sharded halves are built once and reused across candidates (λ is a
    traced scalar), so the sweep composes with the sharded-COO scaling
    story at the cost of K sequential trains rather than one batched
    one.
    """
    if not lams:
        return []
    if cfg.factor_placement == "sharded":
        trainer = ALSTrainer(ratings, n_users, n_items, cfg, mesh=mesh)
        out = []
        for lam in lams:
            U0, V0 = trainer.init_factors()
            U, V = trainer.run(U0, V0, cfg.num_iterations, lam=float(lam))
            out.append(trainer._factors(U, V))
        return out
    if cfg.solver != "xla":
        raise ValueError(
            "sweep_train_als (vmapped form) requires solver='xla'"
        )
    trainer = ALSTrainer(ratings, n_users, n_items, cfg, mesh=mesh)
    side_u, side_i = trainer._user_side, trainer._item_side
    K = len(lams)
    lam_arr = jnp.asarray(list(lams), jnp.float32)
    alpha = jnp.asarray(cfg.alpha, jnp.float32)

    common = dict(
        implicit=cfg.implicit, weighted_lambda=cfg.weighted_lambda,
        precision=cfg.matmul_precision, solver=cfg.solver,
        gather_dtype=cfg.gather_dtype, gather_mode=cfg.gather_mode,
        solver_mode=cfg.solver_mode, subspace_size=cfg.subspace_size,
        fused_gather=trainer.fused_gather or "taa",
    )

    def make_half(side):
        def one(upd, opp, lam):
            return _half_iteration_impl(
                upd, opp, side["c_sorted"], side["v_sorted"],
                side["buckets"], lam, alpha, ks=side["ks"], **common,
            )

        return xray.instrument("als.sweep_half")(
            jax.jit(jax.vmap(one, in_axes=(0, 0, 0)), donate_argnums=(0,))
        )

    half_u = make_half(side_u)
    half_i = make_half(side_i)

    U0, V0 = trainer.init_factors()
    U = jnp.broadcast_to(U0, (K, *U0.shape)) + 0.0   # materialize
    V = jnp.broadcast_to(V0, (K, *V0.shape)) + 0.0
    for _ in range(cfg.num_iterations):
        U = half_u(U, V, lam_arr)
        V = half_i(V, U, lam_arr)
    fence(U, V)
    Uh, Vh = np.asarray(U), np.asarray(V)
    return [
        ALSFactors(user_factors=Uh[k], item_factors=Vh[k]) for k in range(K)
    ]


# --------------------------------------------------------------------------
# Quality metrics
# --------------------------------------------------------------------------


@jax.jit
def _finite_all(U, V):
    """Watchdog NaN/Inf sentinel: one bandwidth-bound reduction over
    both factor tables (noise next to a half-iteration's Gram work)."""
    return jnp.isfinite(U).all() & jnp.isfinite(V).all()


@xray.instrument("als.sq_err_sum")
@jax.jit
def _sq_err_sum(U, V, u, i, v):
    pred = jnp.sum(U[u] * V[i], axis=-1)
    d = pred - v
    return jnp.sum(d * d)


def rmse(
    factors: ALSFactors,
    user_ix: np.ndarray,
    item_ix: np.ndarray,
    rating: np.ndarray,
    chunk: int = 1 << 20,
) -> float:
    """RMSE over COO triples, chunked to bound device memory."""
    U = jnp.asarray(factors.user_factors)
    V = jnp.asarray(factors.item_factors)
    total = 0.0
    n = len(rating)
    for s in range(0, n, chunk):
        e = min(s + chunk, n)
        total += float(
            _sq_err_sum(
                U, V,
                jnp.asarray(user_ix[s:e]),
                jnp.asarray(item_ix[s:e]),
                jnp.asarray(rating[s:e]),
            )
        )
    return float(np.sqrt(total / max(n, 1)))
