"""Multiclass logistic regression on TPU.

The BASELINE.json classification config calls for "NaiveBayes -> TPU
logistic": a softmax classifier trained by full-batch gradient descent under
``jit`` (``lax.scan`` over steps — one compiled program for the whole
training run, no per-step Python dispatch).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
import optax

__all__ = ["LogisticModel", "train_logistic"]


@dataclass
class LogisticModel:
    weights: np.ndarray  # [F, C]
    bias: np.ndarray     # [C]
    labels: np.ndarray   # [C]

    def predict_proba(self, x: np.ndarray) -> np.ndarray:
        logits = np.atleast_2d(x) @ self.weights + self.bias
        e = np.exp(logits - logits.max(axis=-1, keepdims=True))
        return e / e.sum(axis=-1, keepdims=True)

    def predict(self, x: np.ndarray) -> np.ndarray:
        logits = np.atleast_2d(x) @ self.weights + self.bias
        return self.labels[np.argmax(logits, axis=-1)]


def train_logistic(
    features: np.ndarray,
    labels: np.ndarray,
    lr: float = 0.1,
    steps: int = 300,
    l2: float = 1e-4,
) -> LogisticModel:
    x = jnp.asarray(features, jnp.float32)
    classes, y = np.unique(labels, return_inverse=True)
    yj = jnp.asarray(y)
    n_f, n_c = x.shape[1], len(classes)

    params = {
        "w": jnp.zeros((n_f, n_c), jnp.float32),
        "b": jnp.zeros((n_c,), jnp.float32),
    }
    opt = optax.adam(lr)

    def loss_fn(p):
        logits = x @ p["w"] + p["b"]
        ll = jnp.take_along_axis(
            jax.nn.log_softmax(logits), yj[:, None], axis=1
        )
        return -ll.mean() + l2 * (p["w"] ** 2).sum()

    @jax.jit
    def fit(p):
        state = opt.init(p)

        def step(carry, _):
            p, state = carry
            g = jax.grad(loss_fn)(p)
            updates, state = opt.update(g, state)
            p = optax.apply_updates(p, updates)
            return (p, state), None

        (p, _), _ = jax.lax.scan(step, (p, state), None, length=steps)
        return p

    p = fit(params)
    return LogisticModel(
        weights=np.asarray(p["w"]),
        bias=np.asarray(p["b"]),
        labels=classes,
    )
