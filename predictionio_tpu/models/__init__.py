"""Model-family implementations (the MLlib-replacement compute layer)."""

from .als import ALSConfig, ALSFactors, rmse, train_als

__all__ = ["ALSConfig", "ALSFactors", "rmse", "train_als"]
