"""Markov chain next-state model.

Re-expression of reference `e2/engine/MarkovChain.scala:25-90`: a
row-normalized top-N transition matrix built from (state, next-state) pair
counts.  Counting is one segment-sum over pair codes; top-N per row keeps
the model sparse like the reference's ``CoordinateMatrix`` build.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

__all__ = ["MarkovChainModel", "train_markov_chain"]


@dataclass
class MarkovChainModel:
    """Per-state top-N transitions: indices [S, N], probabilities [S, N]
    (prob 0 marks padding)."""

    next_ix: np.ndarray
    prob: np.ndarray
    n_states: int

    def predict(self, state: int) -> list[tuple[int, float]]:
        """Next-state distribution (reference `MarkovChainModel.predict`)."""
        if not (0 <= state < self.n_states):
            return []
        row_p = self.prob[state]
        keep = row_p > 0
        return list(zip(self.next_ix[state][keep].tolist(),
                        row_p[keep].tolist()))


def train_markov_chain(
    from_ix: np.ndarray,
    to_ix: np.ndarray,
    n_states: int,
    top_n: int = 10,
) -> MarkovChainModel:
    pair = from_ix.astype(np.int64) * n_states + to_ix.astype(np.int64)
    uniq, counts = np.unique(pair, return_counts=True)
    rows = (uniq // n_states).astype(np.int64)
    cols = (uniq % n_states).astype(np.int64)

    next_ix = np.zeros((n_states, top_n), dtype=np.int32)
    prob = np.zeros((n_states, top_n), dtype=np.float32)
    order = np.lexsort((-counts, rows))
    rows_s, cols_s, counts_s = rows[order], cols[order], counts[order]
    row_starts = np.searchsorted(rows_s, np.arange(n_states + 1))
    for s in range(n_states):
        lo, hi = row_starts[s], row_starts[s + 1]
        if lo == hi:
            continue
        take = min(top_n, hi - lo)
        c = counts_s[lo : lo + take].astype(np.float32)
        total = counts_s[lo:hi].sum()
        next_ix[s, :take] = cols_s[lo : lo + take]
        prob[s, :take] = c / total
    return MarkovChainModel(next_ix=next_ix, prob=prob, n_states=n_states)
