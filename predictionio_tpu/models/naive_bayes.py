"""Naive Bayes classifiers on TPU.

Replaces Spark MLlib ``NaiveBayes.train`` used by the reference
classification template (`/root/reference/examples/scala-parallel-
classification/add-algorithm/src/main/scala/NaiveBayesAlgorithm.scala:16-28`).
Multinomial NB over non-negative feature vectors: class priors + per-class
feature log-likelihoods via one segment-sum each — two XLA reductions, no
per-row Python.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["NaiveBayesModel", "train_naive_bayes"]


@dataclass
class NaiveBayesModel:
    """log priors [C], log likelihoods [C, F], class labels [C]."""

    log_prior: np.ndarray
    log_likelihood: np.ndarray
    labels: np.ndarray

    def predict_log_scores(self, x: np.ndarray) -> np.ndarray:
        """[.., F] -> [.., C] joint log scores."""
        return x @ self.log_likelihood.T + self.log_prior

    def predict(self, x: np.ndarray) -> np.ndarray:
        """[.., F] -> predicted label per row."""
        scores = self.predict_log_scores(np.atleast_2d(x))
        return self.labels[np.argmax(scores, axis=-1)]


def train_naive_bayes(
    features: np.ndarray,
    labels: np.ndarray,
    lam: float = 1.0,
) -> NaiveBayesModel:
    """Multinomial NB with additive (Laplace) smoothing ``lam``
    (MLlib semantics: lambda defaults to 1.0)."""
    x = jnp.asarray(features, jnp.float32)
    classes, y = np.unique(labels, return_inverse=True)
    yj = jnp.asarray(y)
    n_classes = len(classes)

    class_count = jax.ops.segment_sum(
        jnp.ones(len(y), jnp.float32), yj, num_segments=n_classes
    )
    feat_sum = jax.ops.segment_sum(x, yj, num_segments=n_classes)  # [C, F]

    log_prior = jnp.log(class_count) - jnp.log(class_count.sum())
    smoothed = feat_sum + lam
    log_lik = jnp.log(smoothed) - jnp.log(
        smoothed.sum(axis=1, keepdims=True)
    )
    return NaiveBayesModel(
        log_prior=np.asarray(log_prior),
        log_likelihood=np.asarray(log_lik),
        labels=classes,
    )
