"""Random forest classifier — trees as tensors.

Capability parity with the reference's `RandomForestAlgorithm`
(`examples/scala-parallel-classification/add-algorithm/src/main/scala/
RandomForestAlgorithm.scala:1-60`, MLlib `RandomForest.trainClassifier`),
re-designed for the TPU split of labor:

* **Training is host-side** (numpy): CART split search is data-dependent
  control flow — the worst possible shape for the MXU — and the
  reference's own RandomForestModel is a collected local model (P2L).
  Bootstrap + sqrt-feature subsampling per tree, gini impurity, exact
  threshold search vectorized over candidate splits.
* **Prediction is device-side**: every tree is stored in a COMPLETE
  binary-tree tensor layout (node ``i`` -> children ``2i+1 / 2i+2``), so
  a forest is three arrays — ``feature[t, n]`` (−1 marks a leaf),
  ``threshold[t, n]``, ``label[t, n]`` — and classifying a batch is
  ``max_depth`` gather steps vectorized over (batch × trees) under jit,
  followed by a one-hot majority vote.  No Python control flow, static
  shapes, one executable per (batch, forest) shape.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["ForestConfig", "ForestModel", "train_forest", "forest_predict"]


@dataclass(frozen=True)
class ForestConfig:
    n_trees: int = 16
    max_depth: int = 6
    num_classes: int = 2
    # features sampled per split: sqrt/auto, log2, onethird, all (the
    # reference's MLlib featureSubsetStrategy vocabulary)
    feature_subset: str = "sqrt"
    min_samples_split: int = 2
    seed: int = 0


@dataclass
class ForestModel:
    """Flat complete-binary-tree tensors: [n_trees, 2**(max_depth+1)-1]."""

    feature: np.ndarray     # int32; -1 = leaf
    threshold: np.ndarray   # float32; go left if x[f] <= thr
    label: np.ndarray       # int32 majority label at every node
    num_classes: int
    # input feature width (for deploy-time warmup of the jitted batch
    # walk); -1 on models persisted before this field existed
    n_features: int = -1

    @property
    def max_depth(self) -> int:
        n = self.feature.shape[1]
        return int(np.log2(n + 1)) - 1


def _gini_split(xcol: np.ndarray, onehot: np.ndarray):
    """Best threshold on one feature column by gini; returns
    (impurity, threshold) or (inf, 0) when no split exists.

    ``onehot`` is the node's [n, num_classes] label matrix, built ONCE
    per node by the caller and re-permuted here — rebuilding it for each
    of the k sampled features was the hottest wasted work in training.
    """
    order = np.argsort(xcol, kind="stable")
    xs = xcol[order]
    # candidate boundaries: positions where consecutive x differ
    diff = np.nonzero(xs[1:] != xs[:-1])[0]
    if len(diff) == 0:
        return np.inf, 0.0
    n = len(xs)
    left_counts = np.cumsum(onehot[order], axis=0)  # counts for split at i
    total = left_counts[-1]
    li = left_counts[diff]                        # [C?, num_classes]
    ri = total - li
    nl = li.sum(axis=1)
    nr = ri.sum(axis=1)
    gini_l = 1.0 - ((li / nl[:, None]) ** 2).sum(axis=1)
    gini_r = 1.0 - ((ri / nr[:, None]) ** 2).sum(axis=1)
    w = (nl * gini_l + nr * gini_r) / n
    b = int(np.argmin(w))
    ix = diff[b]
    thr = (xs[ix] + xs[ix + 1]) / 2.0
    return float(w[b]), float(thr)


def _subset_size(strategy: str, n_feat: int) -> int:
    """Features sampled per split (the reference's MLlib
    featureSubsetStrategy values); unknown strategies are an error, not a
    silent fallback."""
    if strategy in ("sqrt", "auto"):
        return max(1, int(np.sqrt(n_feat)))
    if strategy == "log2":
        return max(1, int(np.log2(max(n_feat, 2))))
    if strategy == "onethird":
        return max(1, n_feat // 3)
    if strategy == "all":
        return n_feat
    raise ValueError(
        f"unknown feature_subset {strategy!r}: "
        "expected sqrt/auto/log2/onethird/all"
    )


def _fit_tree(X, y, cfg: ForestConfig, rng: np.random.Generator,
              feature, threshold, label) -> None:
    """Fill one tree's row of the flat tensors."""
    n_nodes = feature.shape[0]
    n_feat = X.shape[1]
    k = _subset_size(cfg.feature_subset, n_feat)
    # worklist of (node index, row indices); traversal order is free —
    # each entry carries its own complete-binary-tree index, children are
    # always enqueued as 2i+1 / 2i+2
    todo: list[tuple[int, np.ndarray]] = [(0, np.arange(len(y)))]
    while todo:
        node, rows = todo.pop()
        ys = y[rows]
        counts = np.bincount(ys, minlength=cfg.num_classes)
        label[node] = int(np.argmax(counts))
        is_last_level = 2 * node + 2 >= n_nodes
        if (
            is_last_level
            or len(rows) < cfg.min_samples_split
            or counts.max() == len(rows)     # pure node
        ):
            continue  # stays a leaf (feature == -1)
        feats = rng.choice(n_feat, size=k, replace=False)
        onehot = np.zeros((len(ys), cfg.num_classes), np.float64)
        onehot[np.arange(len(ys)), ys] = 1.0
        best = (np.inf, 0.0, -1)
        for f in feats:
            imp, thr = _gini_split(X[rows, f], onehot)
            if imp < best[0]:
                best = (imp, thr, int(f))
        if not np.isfinite(best[0]):
            continue  # no separating feature among the sampled ones
        _, thr, f = best
        go_left = X[rows, f] <= thr
        if not go_left.any() or go_left.all():
            continue
        feature[node] = f
        threshold[node] = thr
        todo.append((2 * node + 1, rows[go_left]))
        todo.append((2 * node + 2, rows[~go_left]))


def train_forest(
    X: np.ndarray, y: np.ndarray, cfg: ForestConfig = ForestConfig()
) -> ForestModel:
    """Bootstrap-aggregated CART trees (host-side; see module docstring)."""
    X = np.asarray(X, np.float32)
    y = np.asarray(y, np.int32)
    if len(X) == 0:
        raise ValueError("empty training data")
    n_nodes = 2 ** (cfg.max_depth + 1) - 1
    feature = np.full((cfg.n_trees, n_nodes), -1, np.int32)
    threshold = np.zeros((cfg.n_trees, n_nodes), np.float32)
    label = np.zeros((cfg.n_trees, n_nodes), np.int32)
    rng = np.random.default_rng(cfg.seed)
    for t in range(cfg.n_trees):
        boot = rng.integers(0, len(y), size=len(y))
        _fit_tree(
            X[boot], y[boot], cfg, rng, feature[t], threshold[t], label[t]
        )
    return ForestModel(
        feature=feature, threshold=threshold, label=label,
        num_classes=cfg.num_classes, n_features=X.shape[1],
    )


@partial(jax.jit, static_argnames=("max_depth", "num_classes"))
def _predict_device(
    x, feature, threshold, label, *, max_depth: int, num_classes: int
):
    """[B, F] -> (labels [B], votes [B, num_classes]).

    ``max_depth`` lock-step walk over all (row, tree) pairs: at a leaf
    (feature == -1) the comparison is a no-op and the node index stays
    put, so no per-pair control flow is needed.
    """
    B = x.shape[0]
    T = feature.shape[0]
    node = jnp.zeros((B, T), jnp.int32)

    def step(_, node):
        f = jnp.take_along_axis(feature[None], node[..., None], axis=2)[..., 0]
        thr = jnp.take_along_axis(
            threshold[None], node[..., None], axis=2
        )[..., 0]                                          # [B, T]
        xv = jnp.take_along_axis(
            x[:, None, :], jnp.maximum(f, 0)[..., None], axis=2
        )[..., 0]
        is_leaf = f < 0
        nxt = jnp.where(xv <= thr, 2 * node + 1, 2 * node + 2)
        return jnp.where(is_leaf, node, nxt)

    node = jax.lax.fori_loop(0, max_depth, step, node)
    leaf_lab = jnp.take_along_axis(
        label[None], node[..., None], axis=2
    )[..., 0]                                              # [B, T]
    votes = jnp.sum(
        jax.nn.one_hot(leaf_lab, num_classes, dtype=jnp.float32), axis=1
    )
    return jnp.argmax(votes, axis=1), votes


def forest_predict(
    model: ForestModel, X: np.ndarray, return_votes: bool = False
):
    """Majority-vote classification of a batch (device path)."""
    X = np.atleast_2d(np.asarray(X, np.float32))
    labels, votes = _predict_device(
        X, model.feature, model.threshold, model.label,
        max_depth=model.max_depth, num_classes=model.num_classes,
    )
    labels, votes = jax.device_get((labels, votes))
    return (labels, votes) if return_votes else labels
