"""pio-scout: the serving-side two-stage retrieval layer.

`ops/ann.py` holds the math (quantization, coarse clustering, the
jitted candidate kernels); this package holds the *lifecycle* a serving
process needs around it:

* :class:`RetrievalConfig` — the operator surface (engine.json keys
  ``retrieval`` / ``candidateFactor`` / ``nprobe`` / ``annClusters``,
  CLI + bench knobs), validated once at config time.
* :class:`TwoStageRetriever` — device-cached quantized artifacts
  (int8 table + per-row scale, plus centroids + padded member matrix
  for IVF), a ``search()`` that runs candidate -> exact-rerank and
  books ``pio_retrieval_stage_seconds{stage=candidate|rerank}``, a
  ``warm()`` for the serving compile ladder, and — the pio-live
  contract — an in-place :meth:`TwoStageRetriever.patch` that
  re-quantizes ONLY the rows a fold-in delta touched and appends new
  items to their nearest coarse cluster, no index rebuild.

Tear-freedom follows the repo's delta-apply idiom (`live/apply.py`):
every mutation lands as ONE attribute rebind of the state tuple, so a
concurrent ``search`` sees the old artifact set or the new one, never
a mixed (q_table, scale) pair mis-scaling a row.

The rerank table is deliberately NOT owned here: callers pass the
model's current f32/bf16 device cache per call, because pio-live
rebinds those caches on every delta apply — a retriever-held reference
would serve stale rows after the first fold-in.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass

import numpy as np

from ..obs import RETRIEVAL_STAGE_SECONDS
from ..ops import ann
from ..ops.topk import pow2_ceil, rerank_topk

__all__ = ["RetrievalConfig", "TwoStageRetriever", "RETRIEVAL_MODES"]

RETRIEVAL_MODES = ("exact", "int8", "ivf")

# stage-histogram children cached at import: labels() is too hot for
# the per-query path (same idiom as serving's cached children)
_m_candidate = RETRIEVAL_STAGE_SECONDS.labels(stage="candidate")
_m_rerank = RETRIEVAL_STAGE_SECONDS.labels(stage="rerank")


def _trace_fenced() -> bool:
    return os.environ.get("PIO_TPU_TRACE_RETRIEVAL", "") == "1"


@dataclass(frozen=True)
class RetrievalConfig:
    """How a serving path retrieves top-k.

    ``mode='exact'`` is the pre-scout brute-force scan (the default —
    retrieval stays opt-in per engine.json).  ``'int8'`` adds the flat
    quantized candidate stage; ``'ivf'`` additionally restricts the
    candidate scan to the ``nprobe`` nearest of ``clusters`` coarse
    clusters (``clusters=0`` auto-sizes to ~sqrt(M), pow2-rounded).
    ``candidate_factor`` is the shortlist width in units of k —
    ``candidate_factor * k`` rows survive to the exact rerank."""

    mode: str = "exact"
    candidate_factor: int = 10
    nprobe: int = 8
    clusters: int = 0
    seed: int = 0

    def __post_init__(self) -> None:
        # validated here, not at use sites: these strings arrive
        # straight from user engine.json files, and the use sites
        # dispatch on exact equality with an exact-scan fallthrough —
        # a typo'd mode would silently serve brute force
        if self.mode not in RETRIEVAL_MODES:
            raise ValueError(
                f"retrieval must be one of {RETRIEVAL_MODES}, "
                f"got {self.mode!r}"
            )
        if self.candidate_factor < 1:
            raise ValueError(
                f"candidate_factor must be >= 1, got {self.candidate_factor}"
            )
        if self.nprobe < 1:
            raise ValueError(f"nprobe must be >= 1, got {self.nprobe}")
        if self.clusters < 0:
            raise ValueError(f"clusters must be >= 0, got {self.clusters}")

    @property
    def active(self) -> bool:
        return self.mode != "exact"

    def cache_key(self) -> str:
        """Keys the per-model retriever cache (DeviceTableMixin) the
        way dtype keys the device-table caches."""
        return (
            f"{self.mode}_cf{self.candidate_factor}_np{self.nprobe}"
            f"_c{self.clusters}_s{self.seed}"
        )

    def resolve_clusters(self, n_items: int) -> int:
        if self.clusters > 0:
            return min(self.clusters, max(n_items, 1))
        # ~sqrt(M) balances centroid-scan cost (O(C)) against
        # per-cluster member-scan cost (O(M/C)); pow2 keeps the
        # executable space tidy alongside the pow2 B/k ladders
        return max(min(pow2_ceil(int(np.sqrt(max(n_items, 1)))),
                       max(n_items, 1)), 1)


class TwoStageRetriever:
    """Quantized candidate artifacts + the two-stage search for ONE
    item table (built at model (re)load like the device caches)."""

    def __init__(self, cfg: RetrievalConfig, n_items: int, rank: int,
                 state: dict):
        self.cfg = cfg
        self.n_items = n_items
        self.rank = rank
        # ONE attribute carries every mutable artifact (device arrays
        # + host-side IVF bookkeeping): patch() builds a full
        # replacement dict and rebinds — the tear-freedom contract
        self._state = state
        self.patches = 0

    # -- build -------------------------------------------------------------
    @classmethod
    def build(cls, item_factors: np.ndarray,
              cfg: RetrievalConfig) -> "TwoStageRetriever":
        import jax.numpy as jnp

        table = np.asarray(item_factors, np.float32)
        n_items, rank = table.shape
        q, scale = ann.quantize_rows(table)
        state: dict = {}
        if cfg.mode == "ivf":
            n_clusters = cfg.resolve_clusters(n_items)
            centroids, assign = ann.build_clusters(
                table, n_clusters, seed=cfg.seed
            )
            # skewed catalogs split oversized clusters, so the built
            # count can exceed the requested one — the layout follows
            # the centroids actually produced
            layout = ann.build_cluster_layout(
                q, scale, assign, len(centroids)
            )
            state.update(
                centroids=centroids,           # host: append assignment
                centroids_t=jnp.asarray(
                    np.ascontiguousarray(centroids.T)
                ),
                # the cluster-contiguous slab layout the kernel scans
                q_slabs=jnp.asarray(layout["q_slabs"]),
                slab_scale=jnp.asarray(layout["slab_scale"]),
                slab_ids=jnp.asarray(layout["slab_ids"]),
                # host-side patch addressing: item -> (cluster, slot)
                assign=np.asarray(assign, np.int64),
                slot=layout["slot"],
                fill=layout["fill"],
            )
        else:
            # flat int8 scans: the pre-transposed [R, M] layout the
            # batched serving matmul already established on CPU
            state["scale"] = jnp.asarray(scale)
            state["q_table_t"] = jnp.asarray(
                np.ascontiguousarray(q.T)
            )
        return cls(cfg, n_items, rank, state)

    # -- search ------------------------------------------------------------
    def shortlist_width(self, k: int) -> int:
        """Static candidate count per (k): pow2-rounded so the
        executable key space stays bounded like the B/k ladders."""
        return min(pow2_ceil(self.cfg.candidate_factor * k), self.n_items)

    def search(self, query_vecs, k: int, table):
        """Two-stage top-k: quantized shortlist -> exact rerank against
        ``table`` (the caller's CURRENT unquantized device cache — see
        module docstring for why it is an argument).  Returns
        ``([B, k] values, [B, k] int32 ids)`` with non-finite values
        for shortfall rows, matching the exact scorers' mask contract.
        """
        import jax.numpy as jnp

        st = self._state
        q = jnp.atleast_2d(jnp.asarray(query_vecs, jnp.float32))
        kc = self.shortlist_width(k)
        fence = _trace_fenced()
        t0 = time.perf_counter()
        if self.cfg.mode == "ivf":
            cand = ann.ivf_candidate_topk(
                q, st["centroids_t"], st["q_slabs"], st["slab_scale"],
                st["slab_ids"],
                min(self.cfg.nprobe, st["q_slabs"].shape[0]), kc,
            )
        else:
            cand = ann.int8_candidate_topk(
                q, st["q_table_t"], st["scale"], kc
            )
        if fence:
            cand.block_until_ready()
        t1 = time.perf_counter()
        _m_candidate.observe(t1 - t0)
        vals, ixs = rerank_topk(q, table, cand, min(k, kc))
        if fence:
            vals.block_until_ready()
        _m_rerank.observe(time.perf_counter() - t1)
        return vals, ixs

    def warm(self, k: int, batches, table) -> None:
        """Pre-compile the candidate + rerank executables for every
        batch size in ``batches`` at this k — the two-stage path joins
        the serving warmup ladder so a first real query (or the first
        query after a fold-in) never pays a mid-traffic compile."""
        import jax.numpy as jnp

        for b in batches:
            self.search(
                jnp.zeros((b, self.rank), jnp.float32), k, table
            )

    # -- pio-live delta patch ---------------------------------------------
    def patch(self, ixs, rows, appended=None,
              appended_factors=None) -> dict:
        """Fold one model delta into the quantized index IN PLACE:
        re-quantize only the touched rows, append new items to their
        nearest coarse cluster — never a rebuild (the fold-in
        freshness gate budget has no room for re-clustering a 10M
        catalog).  ``appended_factors`` are the appended rows' f32
        factors (same as ``appended``; the separate name mirrors the
        device-table patch signature).  Returns patch counts."""
        import jax.numpy as jnp

        ixs = np.asarray(ixs, np.int64)
        rows = np.asarray(rows, np.float32) if len(ixs) else \
            np.zeros((0, self.rank), np.float32)
        app = appended if appended is not None else appended_factors
        app = (
            np.asarray(app, np.float32)
            if app is not None and len(app) else None
        )
        if len(ixs) == 0 and app is None:
            return {"patched": 0, "appended": 0}
        st = dict(self._state)
        q_rows, s_rows = (
            ann.quantize_rows(rows) if len(ixs)
            else (np.zeros((0, self.rank), np.int8),
                  np.zeros((0,), np.float32))
        )
        q_app, s_app = (
            ann.quantize_rows(app) if app is not None else (None, None)
        )
        if self.cfg.mode == "ivf":
            self._patch_ivf(st, ixs, q_rows, s_rows, app, q_app, s_app)
        else:
            scale = st["scale"]
            qtt = st["q_table_t"]
            if q_app is not None:
                scale = jnp.concatenate([scale, jnp.asarray(s_app)])
                qtt = jnp.concatenate(
                    [qtt, jnp.asarray(np.ascontiguousarray(q_app.T))],
                    axis=1,
                )
            if len(ixs):
                scale = scale.at[jnp.asarray(ixs)].set(
                    jnp.asarray(s_rows)
                )
                qtt = qtt.at[:, jnp.asarray(ixs)].set(
                    jnp.asarray(q_rows.T)
                )
            st["scale"] = scale
            st["q_table_t"] = qtt
        n_app = 0 if app is None else len(app)
        self.n_items += n_app
        self._state = st
        self.patches += 1
        return {"patched": int(len(ixs)), "appended": n_app}

    def _patch_ivf(self, st, ixs, q_rows, s_rows, app, q_app,
                   s_app) -> None:
        """Patched rows write their (cluster, slot) cells directly
        (the host-side ``slot`` map addresses the slab layout);
        appended rows take the next free slot of their NEAREST
        centroid, growing the padded capacity (one device pad, no
        re-quantization of anything existing) only when a cluster
        fills."""
        import jax.numpy as jnp

        q_slabs = st["q_slabs"]
        slab_scale = st["slab_scale"]
        slab_ids = st["slab_ids"]
        assign = st["assign"]
        slot = st["slot"]
        fill = st["fill"].copy()
        if len(ixs):
            c = jnp.asarray(assign[ixs])
            sl = jnp.asarray(slot[ixs])
            q_slabs = q_slabs.at[c, sl].set(jnp.asarray(q_rows))
            slab_scale = slab_scale.at[c, sl].set(jnp.asarray(s_rows))
        if app is not None:
            clusters = np.asarray(
                ann.nearest_cluster(app, st["centroids"]), np.int64
            )
            new_slots = np.empty(len(app), np.int32)
            for j, c in enumerate(clusters):
                new_slots[j] = fill[c]
                fill[c] += 1
            need = int(fill.max(initial=0))
            cap = q_slabs.shape[1]
            if need > cap:
                grow = int(need * 1.25) + 1 - cap
                q_slabs = jnp.pad(q_slabs, ((0, 0), (0, grow), (0, 0)))
                slab_scale = jnp.pad(slab_scale, ((0, 0), (0, grow)))
                slab_ids = jnp.pad(slab_ids, ((0, 0), (0, grow)),
                                   constant_values=-1)
            c = jnp.asarray(clusters)
            sl = jnp.asarray(new_slots)
            q_slabs = q_slabs.at[c, sl].set(jnp.asarray(q_app))
            slab_scale = slab_scale.at[c, sl].set(jnp.asarray(s_app))
            slab_ids = slab_ids.at[c, sl].set(
                jnp.arange(self.n_items, self.n_items + len(app),
                           dtype=jnp.int32)
            )
            st["assign"] = np.concatenate([assign, clusters])
            st["slot"] = np.concatenate([slot, new_slots])
        st["q_slabs"] = q_slabs
        st["slab_scale"] = slab_scale
        st["slab_ids"] = slab_ids
        st["fill"] = fill

    # -- observability -----------------------------------------------------
    def summary(self) -> dict:
        """Status-JSON block (serving surfaces it as ``retrieval``)."""
        out = {
            "mode": self.cfg.mode,
            "items": self.n_items,
            "candidateFactor": self.cfg.candidate_factor,
            "patches": self.patches,
        }
        if self.cfg.mode == "ivf":
            st = self._state
            out.update(
                clusters=int(st["q_slabs"].shape[0]),
                clusterCapacity=int(st["q_slabs"].shape[1]),
                nprobe=self.cfg.nprobe,
            )
        return out
