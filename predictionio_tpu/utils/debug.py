"""Recursive debug dumper for workflow data.

Analogue of the reference's `WorkflowUtils.debugString`
(`workflow/WorkflowUtils.scala:228-245`), which collects RDDs and walks
arrays/iterables.  Here the interesting container types are jax arrays
(fetched to host, summarized with shape/dtype/sharding), numpy arrays,
dataclasses, and mappings.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

__all__ = ["debug_string"]

_MAX_ITEMS = 20


def _array_summary(x) -> str:
    shape = "x".join(map(str, x.shape)) or "scalar"
    parts = [f"{type(x).__name__}[{shape}] {x.dtype}"]
    sharding = getattr(x, "sharding", None)
    if sharding is not None and getattr(sharding, "spec", None) is not None:
        parts.append(f"spec={sharding.spec}")
    flat = np.asarray(x).reshape(-1)
    if flat.size:
        head = np.array2string(
            flat[:8], precision=4, separator=",", threshold=8
        )
        parts.append(f"head={head}")
    return " ".join(parts)


def debug_string(data: Any, depth: int = 0) -> str:
    """Human dump of arbitrarily nested workflow data structures."""
    if depth > 6:
        return "..."
    if data is None or isinstance(data, (bool, int, float, str, bytes)):
        return repr(data)
    if hasattr(data, "shape") and hasattr(data, "dtype"):
        return _array_summary(data)
    if dataclasses.is_dataclass(data) and not isinstance(data, type):
        inner = ", ".join(
            f"{f.name}={debug_string(getattr(data, f.name), depth + 1)}"
            for f in dataclasses.fields(data)
        )
        return f"{type(data).__name__}({inner})"
    if isinstance(data, dict):
        items = list(data.items())[:_MAX_ITEMS]
        inner = ", ".join(
            f"{k!r}: {debug_string(v, depth + 1)}" for k, v in items
        )
        more = ", ..." if len(data) > _MAX_ITEMS else ""
        return "{" + inner + more + "}"
    if isinstance(data, (list, tuple, set, frozenset)):
        items = list(data)[:_MAX_ITEMS]
        inner = ",".join(debug_string(v, depth + 1) for v in items)
        more = ",..." if len(data) > _MAX_ITEMS else ""
        open_, close = ("[", "]") if isinstance(data, list) else ("(", ")")
        return f"{open_}{inner}{more}{close}"
    return repr(data)
