"""Two-tier logging control (reference `WorkflowUtils.modifyLogging`,
`workflow/WorkflowUtils.scala:277-288`): the root logger and a set of
"chatty" third-party loggers move together — verbose lifts everything,
non-verbose keeps the chatty ones at WARNING so workflow output stays
readable.
"""

from __future__ import annotations

import logging
import sys
from typing import Optional

__all__ = ["setup_logging", "modify_logging", "CHATTY_LOGGERS"]

# the jax/XLA equivalents of the reference's chatty Spark/HBase loggers
CHATTY_LOGGERS = ("jax", "jax._src", "absl", "orbax")

_FORMAT = "%(asctime)s %(levelname).1s %(name)s: %(message)s"


def setup_logging(
    verbose: bool = False,
    debug: bool = False,
    stream=None,
    fmt: Optional[str] = None,
) -> None:
    """Install a stderr handler once and apply the verbosity tiers."""
    root = logging.getLogger()
    if not any(
        isinstance(h, logging.StreamHandler) for h in root.handlers
    ):
        h = logging.StreamHandler(stream or sys.stderr)
        h.setFormatter(logging.Formatter(fmt or _FORMAT))
        root.addHandler(h)
    modify_logging(verbose=verbose, debug=debug)


def modify_logging(verbose: bool = False, debug: bool = False) -> None:
    """Root at DEBUG/INFO, chatty libs one tier quieter — the
    `modifyLogging` contract."""
    root_level = logging.DEBUG if (verbose or debug) else logging.INFO
    chatty_level = logging.INFO if (verbose or debug) else logging.WARNING
    logging.getLogger().setLevel(root_level)
    for name in CHATTY_LOGGERS:
        logging.getLogger(name).setLevel(chatty_level)
