"""Observability + debugging utilities (SURVEY §5 tracing row).

The reference's observability is grizzled-slf4j over log4j with a
verbosity switch (`workflow/WorkflowUtils.scala:277-288`) and a recursive
RDD dumper (`debugString`, `:228-245`).  Here: stdlib logging with the
same two-tier chatty/root split, a pytree-aware debug dumper for
jax/numpy data, and JAX profiler hooks (the Spark-UI replacement — traces
open in TensorBoard/Perfetto).
"""

from .debug import debug_string
from .logging import modify_logging, setup_logging
from .profiling import profile_trace, profiled

__all__ = [
    "debug_string",
    "modify_logging",
    "setup_logging",
    "profile_trace",
    "profiled",
]
