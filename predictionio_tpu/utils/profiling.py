"""JAX profiler hooks — the Spark-UI replacement (SURVEY §5).

Wraps ``jax.profiler`` so workflows can capture device traces without
importing jax at module scope in ops code.  Traces land under
``$PIO_TPU_HOME/profiles/<tag>`` and open in TensorBoard / Perfetto.
"""

from __future__ import annotations

import contextlib
import functools
import logging
import os
import time
from pathlib import Path
from typing import Optional

logger = logging.getLogger(__name__)

__all__ = ["profile_trace", "profiled", "profile_dir"]


def profile_dir(tag: str = "trace") -> Path:
    home = os.environ.get("PIO_TPU_HOME") or os.path.expanduser(
        "~/.predictionio_tpu"
    )
    p = Path(home) / "profiles" / tag
    p.mkdir(parents=True, exist_ok=True)
    return p


@contextlib.contextmanager
def profile_trace(tag: str = "trace", enabled: Optional[bool] = None):
    """Capture a device trace for the enclosed block.

    ``enabled=None`` reads ``PIO_TPU_PROFILE=1`` so production paths can
    carry the hook at zero cost until it's switched on.
    """
    if enabled is None:
        enabled = os.environ.get("PIO_TPU_PROFILE") == "1"
    if not enabled:
        yield None
        return
    import jax

    out = profile_dir(tag)
    t0 = time.perf_counter()
    with jax.profiler.trace(str(out)):
        yield out
    logger.info("profile '%s' captured in %.2fs -> %s",
                tag, time.perf_counter() - t0, out)


def profiled(tag: Optional[str] = None):
    """Decorator form of :func:`profile_trace`."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*a, **kw):
            with profile_trace(tag or fn.__qualname__):
                return fn(*a, **kw)

        return wrapper

    return deco
